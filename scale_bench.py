"""Scale-envelope benchmark: many nodes, many actors, deep task queues.

Role parity: the reference's release benchmarks
(release/benchmarks/README.md:5-12 — many_nodes, many_actors, many_tasks)
scaled to one machine: daemons are in-process (their stores and workers are
real processes), so this measures the CONTROL PLANE's envelope — conductor
RPC latency under N heartbeating nodes, actor registration/creation
throughput, and scheduling latency with a deep queue.

Usage:
    JAX_PLATFORMS=cpu python scale_bench.py [--round 3]
        [--nodes 50] [--actors 100] [--tasks 10000]

Writes SCALE_r{N}.json with --round.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def pctl(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p / 100.0 * len(xs)))]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--actors", type=int, default=100)
    ap.add_argument("--tasks", type=int, default=10000)
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.cluster.protocol import get_client

    results: dict = {"nodes": args.nodes, "actors": args.actors,
                     "tasks": args.tasks}
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 16})
    ray_tpu.init(address=c.address)
    cli = get_client(c.address)
    try:
        # -- many nodes: register N zero-CPU daemons -------------------
        t0 = time.perf_counter()
        for _ in range(args.nodes):
            c.add_node(num_cpus=0, object_store_bytes=32 << 20)
        c.wait_for_nodes(args.nodes + 1, timeout=120)
        results["node_register_per_sec"] = round(
            args.nodes / (time.perf_counter() - t0), 1)

        # control-plane RPC latency under N heartbeating nodes
        lat = []
        for i in range(200):
            t0 = time.perf_counter()
            cli.call("kv_put", ns="scale", key=f"k{i}".encode(), value=b"v")
            lat.append(time.perf_counter() - t0)
        results["kv_put_p50_ms"] = round(pctl(lat, 50) * 1e3, 2)
        results["kv_put_p99_ms"] = round(pctl(lat, 99) * 1e3, 2)

        lat = []
        for _ in range(50):
            t0 = time.perf_counter()
            cli.call("get_nodes")
            lat.append(time.perf_counter() - t0)
        results["get_nodes_p50_ms"] = round(pctl(lat, 50) * 1e3, 2)
        results["get_nodes_p99_ms"] = round(pctl(lat, 99) * 1e3, 2)

        # -- deep queue: N tasks at once -------------------------------
        @ray_tpu.remote
        def nop():
            return None

        ray_tpu.get([nop.remote() for _ in range(50)])  # warm leases
        t0 = time.perf_counter()
        refs = [nop.remote() for _ in range(args.tasks)]
        submit_s = time.perf_counter() - t0
        ray_tpu.get(refs, timeout=600)
        total_s = time.perf_counter() - t0
        results["task_submit_per_sec"] = round(args.tasks / submit_s, 1)
        results["queued_tasks_drained_per_sec"] = round(
            args.tasks / total_s, 1)

        # control plane still responsive right after the storm
        t0 = time.perf_counter()
        cli.call("kv_put", ns="scale", key=b"after", value=b"v")
        results["kv_put_after_storm_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)

        # -- many actors: create in waves, one call each, kill ---------
        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        created = []
        t0 = time.perf_counter()
        wave = 25
        for start in range(0, args.actors, wave):
            batch = [A.options(num_cpus=0.01).remote()
                     for _ in range(min(wave, args.actors - start))]
            ray_tpu.get([a.ping.remote() for a in batch], timeout=600)
            created.extend(batch)
        results["actor_create_call_per_sec"] = round(
            len(created) / (time.perf_counter() - t0), 2)

        # one broadcast round across every live actor
        t0 = time.perf_counter()
        ray_tpu.get([a.ping.remote() for a in created], timeout=600)
        results["actor_broadcast_call_per_sec"] = round(
            len(created) / (time.perf_counter() - t0), 1)
        results["actors_alive"] = sum(
            1 for a in cli.call("list_actors") if a["state"] == "ALIVE")
        for a in created:
            ray_tpu.kill(a)
    finally:
        ray_tpu.shutdown()
        c.shutdown()

    out = {"suite": "ray_tpu scale envelope",
           "reference_analog": "release/benchmarks/README.md:5-12",
           "results": results}
    line = json.dumps(out, indent=2)
    if args.round:
        path = f"SCALE_r{args.round:02d}.json"
        with open(path, "w") as f:
            f.write(line + "\n")
        print(f"wrote {path}")
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
