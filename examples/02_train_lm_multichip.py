"""Multi-chip SPMD LM training on a dp x tp mesh. Off-TPU this simulates
8 devices (run: python examples/02_train_lm_multichip.py)."""
import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax

# The config knob (not the env var) wins over site-installed TPU plugins —
# this demo always simulates a slice with 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import TransformerConfig
from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.train import make_lm_train_step

mesh = build_mesh(MeshSpec(dp=4, tp=2))       # 8 devices: 4-way data, 2-way tensor
cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
                        max_seq=128, attn_impl="reference", dtype=jnp.float32)
init_fn, step_fn, place_batch = make_lm_train_step(cfg, mesh)
state = init_fn(jax.random.PRNGKey(0))

rng = np.random.default_rng(0)
batch = place_batch({"tokens": jnp.asarray(
    rng.integers(0, 1024, (8, 128)), jnp.int32)})
for step in range(5):
    state, metrics = step_fn(state, batch)
    print(f"step {step}: loss={float(metrics['loss']):.4f}")
print("param sharding example:",
      jax.tree_util.tree_leaves(state.params)[0].sharding)
