"""PPO on CartPole via the Algorithm API (run: JAX_PLATFORMS=cpu python
examples/05_rl_cartpole.py)."""
import ray_tpu as rt
from ray_tpu.rl.algorithms import PPOConfig

rt.init(num_cpus=8)  # explicit size: actors HOLD their CPU, so
# leave headroom for tasks scheduled alongside them
config = (PPOConfig().environment("CartPole-v1")
          .rollouts(num_rollout_workers=2, num_envs_per_worker=8))
algo = config.build()
for i in range(5):
    result = algo.train()
    print(f"iter {i}: reward={result['episode_reward_mean']:.1f} "
          f"steps={result['timesteps_total']}")
ckpt = algo.save()
print("checkpoint:", ckpt)
algo.stop()
rt.shutdown()
