"""Text -> packed tokens -> per-rank dataset shards -> train loop (run:
JAX_PLATFORMS=cpu python examples/04_data_pipeline.py)."""
import ray_tpu as rt
from ray_tpu import data
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer

rt.init(num_cpus=8)  # explicit size: actors HOLD their CPU, so
# leave headroom for tasks scheduled alongside them
corpus = [{"text": "jax and pallas and pjit make the chips go brrr. " * 4}
          for _ in range(16)]
ds = data.tokenize_and_pack(data.from_items(corpus, parallelism=4),
                            seq_len=64)
print("packed sequences:", ds.count())


def loop(config):
    from ray_tpu.air import session
    shard = session.get_dataset_shard("train")
    rows = 0
    for batch in shard.iter_batches(batch_size=8):
        rows += len(batch["tokens"])
    session.report({"rank": session.get_world_rank(), "rows": rows})


result = DataParallelTrainer(
    loop, datasets={"train": ds},
    scaling_config=ScalingConfig(num_workers=2,
                                 resources_per_worker={"CPU": 1})).fit()
print("rank-0 metrics:", result.metrics)
rt.shutdown()
