"""Core API tour: tasks, objects, actors (run: JAX_PLATFORMS=cpu python
examples/01_core_api.py)."""
import ray_tpu as rt

rt.init(num_cpus=8)  # explicit size: actors HOLD their CPU, so
# leave headroom for tasks scheduled alongside them


@rt.remote
def square(x):
    return x * x


@rt.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k=1):
        self.n += k
        return self.n


# tasks fan out; refs compose (square-of-square without fetching)
refs = [square.remote(i) for i in range(8)]
print("squares:", rt.get(refs))
print("chained:", rt.get(square.remote(refs[3])))

# objects: put once, share by reference with tasks
big = rt.put(list(range(10_000)))


@rt.remote
def total(xs):
    return sum(xs)


print("sum(big):", rt.get(total.remote(big)))   # the REF travels, not data
print("fractional cpu:", rt.get(square.options(num_cpus=0.5).remote(3)))

# actors: stateful, ordered
c = Counter.remote()
for _ in range(5):
    c.add.remote()
print("count:", rt.get(c.add.remote(0)))

ready, pending = rt.wait([square.remote(2), square.remote(3)], num_returns=1)
print("first ready:", rt.get(ready[0]))
rt.shutdown()
