"""Serve the in-tree LM with KV-cache generation over HTTP (run:
JAX_PLATFORMS=cpu python examples/03_serve_lm.py)."""
import json
import urllib.request

import ray_tpu as rt
from ray_tpu import serve

rt.init(num_cpus=8)  # explicit size: actors HOLD their CPU, so
# leave headroom for tasks scheduled alongside them


@serve.deployment(route_prefix="/generate", init_grace_s=300.0)
class LM:
    def __init__(self):
        from functools import partial

        import jax
        import jax.numpy as jnp

        from ray_tpu.models import (TransformerConfig, generate,
                                    transformer_init)
        self.jnp = jnp
        cfg = TransformerConfig(vocab_size=258, d_model=64, n_layers=2,
                                n_heads=4, n_kv_heads=2, max_seq=128,
                                attn_impl="reference", dtype=jnp.float32)
        self.params = transformer_init(jax.random.PRNGKey(0), cfg)
        self._gen = jax.jit(partial(generate, cfg=cfg, max_new_tokens=16,
                                    temperature=0.8, top_k=40))

    def __call__(self, prompt=None):
        import numpy as np

        from ray_tpu.data import ByteTokenizer
        tok = ByteTokenizer()
        ids = tok.encode(prompt or "hello")[:-1]      # keep it open-ended
        arr = self.jnp.asarray(np.asarray([ids], np.int32))
        out = np.asarray(self._gen(self.params, arr))[0]
        return {"prompt": prompt, "generated_tokens": out.tolist(),
                "text": tok.decode(out)}


handle = serve.run(LM.bind(), http_host="127.0.0.1")
req = urllib.request.Request(
    f"http://127.0.0.1:{handle.http_port}/generate",
    data=json.dumps({"prompt": "tpu"}).encode(),
    headers={"Content-Type": "application/json"})
print(json.loads(urllib.request.urlopen(req, timeout=120).read()))
serve.shutdown()
rt.shutdown()
