"""Cluster launcher: bring a whole cluster up/down from a YAML spec.

Role parity: `ray up/down/attach/exec/submit` (reference
python/ray/scripts/scripts.py:1223 up, :1522 submit; schema
python/ray/autoscaler/ray-schema.json; node bootstrap
python/ray/autoscaler/_private/updater.py). TPU-first differences:

- Workers are provisioned as whole ICI slices through the provider
  (GcpTpuNodeProvider) and bootstrap by STARTUP SCRIPT, not SSH command
  streams — TPU VMs take a metadata startup script natively, which
  removes the reference's ssh/updater machinery from the critical path.
- The monitor (autoscaler + providers) runs inside the head session
  process (`python -m ray_tpu.cluster_launcher --head-session ...`),
  the same placement as the reference's monitor.py on the head node.

YAML schema (subset, see examples/cluster.yaml):

    cluster_name: demo
    provider:
      type: fake | gcp_tpu
      project: my-proj          # gcp_tpu
      zone: us-central2-b       # gcp_tpu
    head:
      port: 6380
      resources: {"CPU": 4}
      dashboard_port: 8265      # optional, -1 disables
    node_types:
      tpu_worker:
        accelerator_type: v5litepod-8   # gcp_tpu
        resources: {"TPU": 8, "CPU": 8}
        min_workers: 1
        max_workers: 4
    max_workers: 8
    idle_timeout_minutes: 5
    setup_commands: ["pip install -e ."]   # gcp_tpu bootstrap extras
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

STATE_DIR = "/tmp/ray_tpu"
# In-process `up` keeps its Popen handle here so `down` can reap the
# exited session (otherwise it lingers as a zombie of the calling
# process; CLI usage reparents to init and needs no reaping).
_SESSIONS: Dict[str, subprocess.Popen] = {}


def _state_path(cluster_name: str) -> str:
    return os.path.join(STATE_DIR, f"launcher-{cluster_name}.json")


def load_config(path: str) -> dict:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict) or "cluster_name" not in cfg:
        raise ValueError(f"{path}: not a cluster config (cluster_name "
                         "missing)")
    cfg.setdefault("provider", {"type": "fake"})
    cfg.setdefault("head", {})
    cfg.setdefault("node_types", {})
    return cfg


def _read_state(cluster_name: str) -> Optional[dict]:
    try:
        with open(_state_path(cluster_name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _build_provider(cfg: dict, conductor_address: str):
    ptype = cfg["provider"].get("type", "fake")
    node_types = cfg.get("node_types", {})
    if ptype == "fake":
        from ray_tpu.autoscaler.autoscaler import FakeNodeProvider
        return FakeNodeProvider(conductor_address, node_types)
    if ptype == "gcp_tpu":
        from ray_tpu.autoscaler.gcp import GcpTpuNodeProvider
        return GcpTpuNodeProvider(
            conductor_address, node_types,
            cluster_name=cfg["cluster_name"],
            project=cfg["provider"].get("project", ""),
            zone=cfg["provider"].get("zone", ""))
    raise ValueError(f"unknown provider type {ptype!r}")


# ---------------------------------------------------------------------------
# head session: conductor + head daemon + provider + autoscaler, one process


def run_head_session(config_path: str) -> None:
    """The long-lived head process `up` spawns (parity: head node =
    gcs + raylet + monitor). Exits cleanly on SIGTERM, terminating
    provider nodes on the way out."""
    from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
    from ray_tpu.cluster.conductor import Conductor
    from ray_tpu.cluster.node_daemon import NodeDaemon

    cfg = load_config(config_path)
    head = cfg.get("head", {})
    port = int(head.get("port", 6380))
    session_dir = os.path.join(STATE_DIR, f"session-{port}")
    os.makedirs(session_dir, exist_ok=True)
    # No journal recovery here: every `up` is a NEW cluster, and a journal
    # from a previous same-port cluster would resurrect its dead node
    # entries as briefly-"alive" (until the health timeout), handing
    # `submit` a dead head address. Same-port failover belongs to
    # `start --head`, not the launcher. The journal is the
    # conductor.log/.snap file pair (persistence.py StateJournal).
    for suffix in (".log", ".snap", ".snap.tmp"):
        try:
            os.unlink(os.path.join(session_dir, "conductor" + suffix))
        except OSError:
            pass
    conductor = Conductor(host=head.get("host", "127.0.0.1"), port=port,
                          persist_dir=session_dir)
    daemon = NodeDaemon(conductor.address,
                        resources=head.get("resources"),
                        is_head=True, session_dir=session_dir,
                        object_store_bytes=int(
                            head.get("object_store_memory_mb", 512)) << 20)
    dash_port = int(head.get("dashboard_port", -1))
    if dash_port >= 0:
        from ray_tpu.dashboard import Dashboard
        try:
            Dashboard(conductor.address, port=dash_port)
        except OSError:
            pass
    provider = _build_provider(cfg, conductor.address)
    node_types = cfg.get("node_types", {})
    # Floor the cluster at min_workers per type before demand exists.
    for tname, tcfg in node_types.items():
        for _ in range(int(tcfg.get("min_workers", 0))):
            provider.create_node(tname)
    scaler = StandardAutoscaler(
        conductor.address, provider, node_types,
        idle_timeout_s=float(cfg.get("idle_timeout_minutes", 5)) * 60,
        max_workers=int(cfg.get("max_workers", 20)),
        min_per_type={t: int(c.get("min_workers", 0))
                      for t, c in node_types.items()})
    scaler.start()

    state = {"pid": os.getpid(), "address": conductor.address,
             "config_path": os.path.abspath(config_path),
             "cluster_name": cfg["cluster_name"]}
    os.makedirs(STATE_DIR, exist_ok=True)
    tmp = _state_path(cfg["cluster_name"]) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, _state_path(cfg["cluster_name"]))
    print(f"HEAD_READY {conductor.address}", flush=True)
    # The `up` CLI closes our pipe after HEAD_READY; route further output
    # to the session log so nothing ever hits a broken pipe.
    log_fd = os.open(os.path.join(session_dir, "launcher.log"),
                     os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    os.dup2(log_fd, 1)
    os.dup2(log_fd, 2)
    os.close(log_fd)

    stop = {"flag": False}

    def on_term(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    while not stop["flag"]:
        time.sleep(0.2)
    # Orderly teardown: provider nodes first (cloud cost), then local.
    def _mark(msg):
        print(f"[teardown +{time.monotonic() - t0:.1f}s] {msg}",
              flush=True)
    t0 = time.monotonic()
    scaler.stop()
    _mark("scaler stopped")
    for pid_, _t in provider.non_terminated_nodes():
        try:
            provider.terminate_node(pid_)
        except Exception:
            pass
        _mark(f"provider node {pid_} terminated")
    daemon.stop()
    _mark("head daemon stopped")
    conductor.stop()
    _mark("conductor stopped")
    try:
        os.unlink(_state_path(cfg["cluster_name"]))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# CLI verbs


def up(config_path: str, wait_s: float = 120.0) -> str:
    """Bring the cluster up; returns the head address. Idempotent: a
    live cluster with this name is left as-is."""
    cfg = load_config(config_path)
    st = _read_state(cfg["cluster_name"])
    if st is not None:
        try:
            os.kill(st["pid"], 0)
            print(f"cluster {cfg['cluster_name']!r} already up at "
                  f"{st['address']}")
            return st["address"]
        except ProcessLookupError:
            pass  # stale state; relaunch
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "PYTHONPATH": pkg_parent + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.cluster_launcher",
         "--head-session", os.path.abspath(config_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, start_new_session=True)   # survives this CLI exiting
    deadline = time.monotonic() + wait_s
    address = None
    # Deadline-aware poll: a head session that wedges BEFORE printing
    # HEAD_READY (TPU init hang, import deadlock) keeps the pipe open and
    # a bare readline() would block this CLI forever.
    while time.monotonic() < deadline:
        remaining = deadline - time.monotonic()
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, min(remaining, 0.5)))
        if not ready:
            if proc.poll() is not None:
                break  # session died without HEAD_READY
            continue
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("HEAD_READY"):
            address = line.split()[1]
            break
    if address is None:
        proc.terminate()
        raise RuntimeError(
            f"head session failed to come up within {wait_s}s")
    proc.stdout.close()   # detach; the session runs on
    _SESSIONS[cfg["cluster_name"]] = proc
    print(f"cluster {cfg['cluster_name']!r} up at {address}")
    min_total = sum(int(t.get("min_workers", 0))
                    for t in cfg.get("node_types", {}).values())
    if min_total:
        _wait_for_nodes(address, 1 + min_total, wait_s)
    return address


def _wait_for_nodes(address: str, n: int, wait_s: float) -> None:
    from ray_tpu.cluster.protocol import get_client
    cli = get_client(address)
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        try:
            nodes = [x for x in cli.call("get_nodes") if x.get("alive",
                                                               True)]
            if len(nodes) >= n:
                return
        except Exception:
            pass
        time.sleep(0.5)
    print(f"warning: cluster has not reached {n} nodes within {wait_s}s",
          file=sys.stderr)


def down(config_path: str, wait_s: float = 60.0) -> None:
    """Tear the cluster down: SIGTERM the head session (which terminates
    provider nodes), then belt-and-braces delete any labeled stragglers
    for cloud providers."""
    cfg = load_config(config_path)
    st = _read_state(cfg["cluster_name"])
    proc = _SESSIONS.pop(cfg["cluster_name"], None)
    if st is not None:
        try:
            os.kill(st["pid"], signal.SIGTERM)
        except ProcessLookupError:
            st = None
    if proc is not None:
        # In-process `up`: wait on the handle (also reaps — a bare
        # kill(pid, 0) loop would see the zombie as alive forever).
        try:
            proc.wait(timeout=wait_s)
        except Exception:
            proc.kill()
            proc.wait()
    elif st is not None:
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            try:
                os.kill(st["pid"], 0)
            except ProcessLookupError:
                break
            time.sleep(0.2)
    # Cloud stragglers: the head may have died without teardown. The
    # fake provider's nodes die with the head process; gcp ones do not.
    if cfg["provider"].get("type") == "gcp_tpu":
        provider = _build_provider(cfg, st["address"] if st else "")
        for pid_, _t in provider.non_terminated_nodes():
            try:
                provider.terminate_node(pid_)
            except Exception:
                pass
    try:
        os.unlink(_state_path(cfg["cluster_name"]))
    except OSError:
        pass
    from ray_tpu.cluster import hygiene
    hygiene.sweep_stale()
    print(f"cluster {cfg['cluster_name']!r} down")


def get_head_address(config_path: str) -> str:
    cfg = load_config(config_path)
    st = _read_state(cfg["cluster_name"])
    if st is None:
        raise SystemExit(f"cluster {cfg['cluster_name']!r} is not up "
                         "(no launcher state)")
    return st["address"]


def exec_cmd(config_path: str, command: str) -> int:
    """Run a shell command against the cluster (RAY_TPU_ADDRESS set),
    parity: `ray exec`. Local head: direct subprocess."""
    address = get_head_address(config_path)
    env = {**os.environ, "RAY_TPU_ADDRESS": address}
    return subprocess.call(command, shell=True, env=env)


def attach(config_path: str) -> int:
    """Interactive shell wired to the cluster (parity: `ray attach`)."""
    address = get_head_address(config_path)
    shell = os.environ.get("SHELL", "/bin/bash")
    env = {**os.environ, "RAY_TPU_ADDRESS": address}
    print(f"attaching to {address} (RAY_TPU_ADDRESS set; exit to detach)")
    return subprocess.call([shell], env=env)


def submit(config_path: str, entrypoint: str,
           working_dir: Optional[str] = None, follow: bool = True) -> str:
    """Submit a job to the cluster (parity: `ray submit` /
    `ray job submit`)."""
    from ray_tpu.job_submission import JobSubmissionClient
    address = get_head_address(config_path)
    client = JobSubmissionClient(address)
    sid = client.submit_job(
        entrypoint=entrypoint,
        runtime_env={"working_dir": working_dir} if working_dir else None)
    print(f"submitted job {sid}")
    if follow:
        for chunk in client.tail_job_logs(sid):
            sys.stdout.write(chunk)
            sys.stdout.flush()
        print(f"job {sid}: {client.get_job_status(sid)}")
    return sid


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser("ray_tpu.cluster_launcher")
    ap.add_argument("--head-session", metavar="CONFIG",
                    help="(internal) run the head session in-process")
    args = ap.parse_args(argv)
    if args.head_session:
        run_head_session(args.head_session)


if __name__ == "__main__":
    main()
