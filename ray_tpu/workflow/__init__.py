"""ray_tpu.workflow — durable DAG execution.

Parity surface: reference python/ray/workflow (workflow_executor.py,
workflow_state_from_storage.py, workflow_storage.py, the event system):
run a DAG of tasks where every step's result is checkpointed to
pluggable storage; a crashed/resumed workflow skips completed steps and
recomputes only the rest. Steps may return ``continuation(sub_dag)``
(dynamic workflows) and wait on externally-delivered ``event``s.
"""

from ray_tpu.workflow.execution import (continuation, delete, event,
                                        get_output, get_status, list_all,
                                        resume, run, run_async, send_event,
                                        set_storage)

__all__ = ["run", "run_async", "resume", "get_output", "get_status",
           "list_all", "delete", "continuation", "event", "send_event",
           "set_storage"]
