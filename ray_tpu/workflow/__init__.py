"""ray_tpu.workflow — durable DAG execution.

Parity surface: reference python/ray/workflow (workflow_executor.py,
workflow_state_from_storage.py): run a DAG of tasks where every step's
result is checkpointed to storage; a crashed/resumed workflow skips
completed steps and recomputes only the rest.
"""

from ray_tpu.workflow.execution import (delete, get_output, get_status,
                                        list_all, resume, run, run_async)

__all__ = ["run", "run_async", "resume", "get_output", "get_status",
           "list_all", "delete"]
