"""Pluggable workflow storage.

Role parity: python/ray/workflow storage layer (workflow_storage.py) —
step checkpoints, workflow metadata, and events live behind a small
byte-blob interface so the backend can be a local directory (default),
an fsspec URI (gs://, s3://, file://), or the in-memory mock:// store
(tests). Selected via ``workflow.set_storage(url)`` or the
RTPU_WORKFLOW_STORAGE env var.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import List, Optional


class Storage:
    def put_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> List[str]:
        """Immediate child names under prefix (directory-style)."""
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError


class FileStorage(Storage):
    def __init__(self, root: str):
        self.root = root

    def _p(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put_bytes(self, key: str, data: bytes) -> None:
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic commit

    def get_bytes(self, key: str) -> bytes:
        with open(self._p(key), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return os.path.exists(self._p(key))

    def list_prefix(self, prefix: str) -> List[str]:
        d = self._p(prefix)
        if not os.path.isdir(d):
            return []
        return sorted(os.listdir(d))

    def delete_prefix(self, prefix: str) -> None:
        shutil.rmtree(self._p(prefix), ignore_errors=True)


class UriStorage(Storage):
    """Any tune-syncer backend scheme (mock://, fsspec gs/s3/file)."""

    def __init__(self, uri_root: str):
        from ray_tpu.tune.syncer import backend_for
        self.uri_root = uri_root.rstrip("/")
        self._backend = backend_for(uri_root)
        # Byte-level ops ride a per-key staging file through the backend's
        # dir-level API (it is the stable surface all three schemes share).
        self._stage = tempfile.mkdtemp(prefix="rtpu-wfstage-")

    def _key_uri(self, key: str) -> str:
        return f"{self.uri_root}/{key}".rstrip("/")

    def put_bytes(self, key: str, data: bytes) -> None:
        d = os.path.join(self._stage, "put")
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
        with open(os.path.join(d, "blob"), "wb") as f:
            f.write(data)
        self._backend.upload_dir(d, self._key_uri(key))

    def get_bytes(self, key: str) -> bytes:
        d = os.path.join(self._stage, "get")
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
        self._backend.download_dir(self._key_uri(key), d)
        with open(os.path.join(d, "blob"), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return self._backend.exists(self._key_uri(key))

    def list_prefix(self, prefix: str) -> List[str]:
        # mock backend: keys are whole-dir uploads keyed by URI
        from ray_tpu.tune.syncer import _MockBackend
        if isinstance(self._backend, _MockBackend):
            base = self._key_uri(prefix)
            out = set()
            for uri in _MockBackend.store:
                if uri.startswith(base + "/"):
                    out.add(uri[len(base) + 1:].split("/")[0])
            return sorted(out)
        import fsspec
        from urllib.parse import urlparse
        p = urlparse(self._key_uri(prefix))
        fs = fsspec.filesystem(p.scheme)
        base = (p.netloc + p.path).rstrip("/")
        try:
            return sorted({e.rstrip("/").rsplit("/", 1)[-1]
                           for e in fs.ls(base, detail=False)})
        except FileNotFoundError:
            return []

    def delete_prefix(self, prefix: str) -> None:
        from ray_tpu.tune.syncer import _MockBackend
        if isinstance(self._backend, _MockBackend):
            base = self._key_uri(prefix)
            for uri in list(_MockBackend.store):
                if uri == base or uri.startswith(base + "/"):
                    del _MockBackend.store[uri]
            return
        import fsspec
        from urllib.parse import urlparse
        p = urlparse(self._key_uri(prefix))
        fs = fsspec.filesystem(p.scheme)
        try:
            fs.rm((p.netloc + p.path).rstrip("/"), recursive=True)
        except FileNotFoundError:
            pass


_DEFAULT_ROOT = os.path.join(tempfile.gettempdir(), "rtpu_workflows")
_storage: Optional[Storage] = None
_storage_url: Optional[str] = None


def storage_for(url: str) -> Storage:
    """Backend instance for a URL without touching the process global —
    remote steps (event waiters) receive the driver's URL explicitly."""
    from ray_tpu.tune.syncer import is_uri
    return UriStorage(url) if is_uri(url) else FileStorage(url)


def set_storage(url: str) -> None:
    """Select the workflow storage backend (parity: workflow.init's
    storage URL)."""
    global _storage, _storage_url
    _storage = storage_for(url)
    _storage_url = url


def get_storage_url() -> str:
    if _storage_url is None:
        return os.environ.get("RTPU_WORKFLOW_STORAGE", _DEFAULT_ROOT)
    return _storage_url


def get_storage() -> Storage:
    global _storage
    if _storage is None:
        set_storage(get_storage_url())
    return _storage


def reset_storage() -> None:
    """Back to the env/default selection (test teardown)."""
    global _storage, _storage_url
    _storage = None
    _storage_url = None
