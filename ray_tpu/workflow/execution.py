"""Durable DAG executor.

Each DAG node becomes a *step* with a deterministic step-id (the node's
position in a post-order walk + function name). Before running a step the
executor checks storage; a hit short-circuits the whole subtree (parity:
workflow_state_from_storage.py recovery semantics). Results persist as
pickle files under <storage>/<workflow_id>/steps/.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.dag.nodes import DAGNode, FunctionNode, InputNode

_DEFAULT_STORAGE = os.path.join(tempfile.gettempdir(), "rtpu_workflows")
_storage_root = os.environ.get("RTPU_WORKFLOW_STORAGE", _DEFAULT_STORAGE)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root, workflow_id)


def _step_path(workflow_id: str, step_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), "steps", f"{step_id}.pkl")


def _assign_step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic ids: post-order index + callable name."""
    order: List[DAGNode] = []
    seen = set()

    def walk(node: DAGNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child in node._children():
            walk(child)
        order.append(node)

    walk(dag)
    ids = {}
    for i, node in enumerate(order):
        name = ""
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "fn")
        elif isinstance(node, InputNode):
            name = "input"
        ids[id(node)] = f"{i:04d}_{name}"
    return ids


def _execute_durable(node: DAGNode, workflow_id: str,
                     step_ids: Dict[int, str], memo: Dict[int, Any],
                     input_value) -> Any:
    import ray_tpu as rt
    from ray_tpu.core.refs import ObjectRef

    key = id(node)
    if key in memo:
        return memo[key]
    step_id = step_ids[key]
    path = _step_path(workflow_id, step_id)
    if os.path.exists(path):
        with open(path, "rb") as f:
            out = pickle.load(f)
        memo[key] = out
        return out
    if isinstance(node, InputNode):
        out = input_value
    else:
        def rv(v):
            return _execute_durable(v, workflow_id, step_ids, memo,
                                    input_value) if isinstance(v, DAGNode) \
                else v
        args = tuple(rv(a) for a in node._bound_args)
        kwargs = {k: rv(v) for k, v in node._bound_kwargs.items()}
        if isinstance(node, FunctionNode):
            out = rt.get(node._remote_fn.remote(*args, **kwargs))
        else:
            raise TypeError(
                f"workflow DAGs support function nodes and InputNode; got "
                f"{type(node).__name__} (actor nodes are not durable)")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(out, f, protocol=5)
    os.replace(tmp, path)  # atomic commit of the step checkpoint
    memo[key] = out
    return out


def _set_status(workflow_id: str, status: str, dag_blob: Optional[bytes],
                input_blob: Optional[bytes] = None) -> None:
    d = _wf_dir(workflow_id)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "status"), "w") as f:
        f.write(status)
    if dag_blob is not None:
        with open(os.path.join(d, "dag.pkl"), "wb") as f:
            f.write(dag_blob)
    if input_blob is not None:
        with open(os.path.join(d, "input.pkl"), "wb") as f:
            f.write(input_blob)


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute a DAG durably; returns the final result."""
    import uuid

    import cloudpickle
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:8]}"
    _set_status(workflow_id, "RUNNING", cloudpickle.dumps(dag),
                cloudpickle.dumps(input_value))
    step_ids = _assign_step_ids(dag)
    try:
        out = _execute_durable(dag, workflow_id, step_ids, {}, input_value)
    except BaseException:
        _set_status(workflow_id, "FAILED", None)
        raise
    with open(os.path.join(_wf_dir(workflow_id), "output.pkl"), "wb") as f:
        pickle.dump(out, f, protocol=5)
    _set_status(workflow_id, "SUCCESSFUL", None)
    return out


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Returns a concurrent.futures.Future of run()."""
    from concurrent.futures import Future
    fut: Future = Future()

    def go():
        try:
            fut.set_result(run(dag, workflow_id=workflow_id,
                               input_value=input_value))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=go, daemon=True).start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed steps are read from storage."""
    import cloudpickle
    d = _wf_dir(workflow_id)
    with open(os.path.join(d, "dag.pkl"), "rb") as f:
        dag = cloudpickle.load(f)
    input_value = None
    input_path = os.path.join(d, "input.pkl")
    if os.path.exists(input_path):
        with open(input_path, "rb") as f:
            input_value = cloudpickle.load(f)
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def get_output(workflow_id: str) -> Any:
    with open(os.path.join(_wf_dir(workflow_id), "output.pkl"), "rb") as f:
        return pickle.load(f)


def get_status(workflow_id: str) -> str:
    path = os.path.join(_wf_dir(workflow_id), "status")
    if not os.path.exists(path):
        return "NOT_FOUND"
    return open(path).read().strip()


def list_all() -> List[tuple]:
    if not os.path.isdir(_storage_root):
        return []
    return [(wf, get_status(wf)) for wf in sorted(os.listdir(_storage_root))]


def delete(workflow_id: str) -> None:
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
