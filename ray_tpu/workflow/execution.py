"""Durable DAG executor: static DAGs, dynamic continuations, events.

Each DAG node becomes a *step* with a deterministic step-id (the node's
position in a post-order walk + function name). Before running a step the
executor checks storage; a hit short-circuits the whole subtree (parity:
workflow_state_from_storage.py recovery semantics). Results persist as
pickle blobs behind the pluggable storage interface (storage.py; parity:
workflow_storage.py).

Dynamic workflows (parity: workflow_executor.py continuation handling):
a step may return ``workflow.continuation(sub_dag)`` — the sub-DAG
replaces the step, executing durably with step-ids namespaced under the
parent, and its result becomes the step's checkpointed result. Recursion
through continuations expresses loops/recursion the static DAG cannot.

Events (parity: python/ray/workflow event system): ``workflow.event(n)``
is a step that completes only once ``workflow.send_event(workflow_id, n,
payload)`` delivers a payload through storage — so a resumed workflow
sees an already-delivered event without re-waiting.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.dag.nodes import DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.storage import get_storage, set_storage  # noqa: F401


class Continuation:
    """Wrapper a step returns to hand control to a sub-DAG."""

    def __init__(self, dag: DAGNode, input_value: Any = None):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation() takes a bound DAG node "
                            "(fn.bind(...))")
        self.dag = dag
        self.input_value = input_value


def continuation(dag: DAGNode, input_value: Any = None) -> Continuation:
    return Continuation(dag, input_value)


def _step_key(workflow_id: str, step_id: str) -> str:
    return f"{workflow_id}/steps/{step_id}.pkl"


def _assign_step_ids(dag: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic ids: post-order index + callable name."""
    order: List[DAGNode] = []
    seen = set()

    def walk(node: DAGNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child in node._children():
            walk(child)
        order.append(node)

    walk(dag)
    ids = {}
    for i, node in enumerate(order):
        name = ""
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "fn")
        elif isinstance(node, InputNode):
            name = "input"
        ids[id(node)] = f"{prefix}{i:04d}_{name}"
    return ids


def _checkpoint(workflow_id: str, step_id: str, value: Any) -> None:
    get_storage().put_bytes(_step_key(workflow_id, step_id),
                            pickle.dumps(value, protocol=5))


def _execute_durable(node: DAGNode, workflow_id: str,
                     step_ids: Dict[int, str], memo: Dict[int, Any],
                     input_value) -> Any:
    import ray_tpu as rt

    store = get_storage()
    key = id(node)
    if key in memo:
        return memo[key]
    step_id = step_ids[key]
    skey = _step_key(workflow_id, step_id)
    if store.exists(skey):
        out = pickle.loads(store.get_bytes(skey))
        memo[key] = out
        return out
    if isinstance(node, InputNode):
        out = input_value
    else:
        def rv(v):
            return _execute_durable(v, workflow_id, step_ids, memo,
                                    input_value) if isinstance(v, DAGNode) \
                else v
        args = tuple(rv(a) for a in node._bound_args)
        kwargs = {k: rv(v) for k, v in node._bound_kwargs.items()}
        if isinstance(node, FunctionNode):
            out = rt.get(node._remote_fn.remote(*args, **kwargs))
        else:
            raise TypeError(
                f"workflow DAGs support function nodes and InputNode; got "
                f"{type(node).__name__} (actor nodes are not durable)")
        if isinstance(out, Continuation):
            # Dynamic workflow: the sub-DAG replaces this step. Its own
            # steps checkpoint under a namespaced prefix, so resume
            # re-enters the continuation and skips its finished parts.
            # Placeholders (workflow.event() inside the continuation) get
            # THIS workflow's id — without it the event step polls a key
            # under the placeholder repr and hangs forever.
            sub_dag = _inject_workflow_id(out.dag, workflow_id)
            sub_ids = _assign_step_ids(sub_dag, prefix=f"{step_id}.c/")
            out = _execute_durable(sub_dag, workflow_id, sub_ids, {},
                                   out.input_value)
    _checkpoint(workflow_id, step_id, out)
    memo[key] = out
    return out


# ---------------------------------------------------------------------------
# events


def _event_key(workflow_id: str, name: str) -> str:
    return f"{workflow_id}/events/{name}.pkl"


def send_event(workflow_id: str, name: str, payload: Any = None) -> None:
    """Deliver an external event through storage; the waiting step (and
    any resumed re-run) observes it durably."""
    get_storage().put_bytes(_event_key(workflow_id, name),
                            pickle.dumps(payload, protocol=5))


def _wait_event_fn(workflow_id: str, name: str, timeout_s: Optional[float],
                   poll_s: float, storage_url: str):
    # Runs in a WORKER: the driver's storage selection doesn't exist
    # here, so the step carries the URL.
    from ray_tpu.workflow.storage import storage_for
    store = storage_for(storage_url)
    k = _event_key(workflow_id, name)
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        if store.exists(k):
            return pickle.loads(store.get_bytes(k))
        if deadline is not None and time.monotonic() >= deadline:
            raise TimeoutError(
                f"workflow event {name!r} not delivered in {timeout_s}s")
        time.sleep(poll_s)


def event(name: str, *, timeout_s: Optional[float] = None,
          poll_s: float = 0.2) -> DAGNode:
    """A DAG step that completes when ``send_event(workflow_id, name)``
    delivers a payload; evaluates to that payload. The workflow id is
    injected at run() time."""
    import ray_tpu as rt

    from ray_tpu.workflow.storage import get_storage_url
    fn = rt.remote(_wait_event_fn).options(num_cpus=0.01)
    node = fn.bind(_WorkflowIdPlaceholder(), name, timeout_s, poll_s,
                   get_storage_url())
    return node


class _WorkflowIdPlaceholder:
    """Replaced with the actual workflow id when run() walks the DAG."""


def _inject_workflow_id(dag: DAGNode, workflow_id: str) -> DAGNode:
    """Return a COPY of ``dag`` with every _WorkflowIdPlaceholder replaced.
    Non-destructive: the caller's DAG keeps its placeholders, so the same
    DAG object can be run again under a different workflow_id, and a
    continuation's sub-DAG (built once inside user code) can be injected
    at every incarnation. Shared nodes stay shared in the copy (memo), so
    step identity by ``id(node)`` still dedupes diamonds."""
    import copy
    memo: Dict[int, DAGNode] = {}

    def sub(v):
        if isinstance(v, _WorkflowIdPlaceholder):
            return workflow_id
        if isinstance(v, DAGNode):
            return walk(v)
        return v

    def walk(node: DAGNode) -> DAGNode:
        got = memo.get(id(node))
        if got is not None:
            return got
        clone = copy.copy(node)
        memo[id(node)] = clone
        clone._bound_args = tuple(sub(a) for a in node._bound_args)
        clone._bound_kwargs = {k: sub(v)
                               for k, v in node._bound_kwargs.items()}
        return clone

    return walk(dag)


# ---------------------------------------------------------------------------
# workflow lifecycle


def _set_status(workflow_id: str, status: str, dag_blob: Optional[bytes],
                input_blob: Optional[bytes] = None) -> None:
    store = get_storage()
    store.put_bytes(f"{workflow_id}/status", status.encode())
    if dag_blob is not None:
        store.put_bytes(f"{workflow_id}/dag.pkl", dag_blob)
    if input_blob is not None:
        store.put_bytes(f"{workflow_id}/input.pkl", input_blob)


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute a DAG durably; returns the final result."""
    import uuid

    import cloudpickle
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:8]}"
    # Persist the PRE-injection DAG (placeholders intact): resume() re-runs
    # it under its stored id, and the user's object stays reusable under a
    # different workflow_id.
    _set_status(workflow_id, "RUNNING", cloudpickle.dumps(dag),
                cloudpickle.dumps(input_value))
    dag = _inject_workflow_id(dag, workflow_id)
    step_ids = _assign_step_ids(dag)
    try:
        out = _execute_durable(dag, workflow_id, step_ids, {}, input_value)
    except BaseException:  # noqa: BLE001 - durably mark FAILED, then re-raise
        _set_status(workflow_id, "FAILED", None)
        raise
    get_storage().put_bytes(f"{workflow_id}/output.pkl",
                            pickle.dumps(out, protocol=5))
    _set_status(workflow_id, "SUCCESSFUL", None)
    return out


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Returns a concurrent.futures.Future of run()."""
    from concurrent.futures import Future
    fut: Future = Future()

    def go():
        try:
            fut.set_result(run(dag, workflow_id=workflow_id,
                               input_value=input_value))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=go, daemon=True, name="workflow-run-async").start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed steps are read from storage."""
    import cloudpickle
    store = get_storage()
    dag = cloudpickle.loads(store.get_bytes(f"{workflow_id}/dag.pkl"))
    input_value = None
    if store.exists(f"{workflow_id}/input.pkl"):
        input_value = cloudpickle.loads(
            store.get_bytes(f"{workflow_id}/input.pkl"))
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def get_output(workflow_id: str) -> Any:
    return pickle.loads(get_storage().get_bytes(f"{workflow_id}/output.pkl"))


def get_status(workflow_id: str) -> str:
    store = get_storage()
    if not store.exists(f"{workflow_id}/status"):
        return "NOT_FOUND"
    return store.get_bytes(f"{workflow_id}/status").decode().strip()


def list_all() -> List[tuple]:
    store = get_storage()
    return [(wf, get_status(wf)) for wf in store.list_prefix("")]


def delete(workflow_id: str) -> None:
    get_storage().delete_prefix(workflow_id)
