"""Core-runtime microbenchmark (parity: python/ray/_private/ray_perf.py:93
`ray microbenchmark` — task/actor/object op throughput and latency)."""

from __future__ import annotations

import time
from typing import Optional

import numpy as np


def _rate(n: int, seconds: float) -> str:
    return f"{n / seconds:,.0f}/s"


def run_microbenchmark(address: Optional[str] = None) -> dict:
    import ray_tpu as rt
    if address:
        rt.init(address=address, ignore_reinit_error=True)
    else:
        rt.init(ignore_reinit_error=True)
    results = {}

    @rt.remote
    def noop():
        return None

    @rt.remote
    class Pinger:
        def ping(self):
            return None

    # warm up the lease/worker path
    rt.get([noop.remote() for _ in range(10)])

    n = 300
    t0 = time.perf_counter()
    rt.get([noop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    results["tasks_per_second"] = n / dt
    print(f"tasks (batch submit+get): {_rate(n, dt)}")

    t0 = time.perf_counter()
    for _ in range(50):
        rt.get(noop.remote())
    dt = time.perf_counter() - t0
    results["task_roundtrip_ms"] = dt / 50 * 1e3
    print(f"single task round-trip: {dt / 50 * 1e3:.2f} ms")

    actor = Pinger.remote()
    rt.get(actor.ping.remote())
    n = 500
    t0 = time.perf_counter()
    rt.get([actor.ping.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    results["actor_calls_per_second"] = n / dt
    print(f"actor calls (pipelined): {_rate(n, dt)}")

    t0 = time.perf_counter()
    for _ in range(100):
        rt.get(actor.ping.remote())
    dt = time.perf_counter() - t0
    results["actor_roundtrip_ms"] = dt / 100 * 1e3
    print(f"single actor call round-trip: {dt / 100 * 1e3:.2f} ms")
    rt.kill(actor)

    for mb in (1, 64):
        arr = np.random.rand(mb << 17)  # mb MB of float64
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            ref = rt.put(arr)
            out = rt.get(ref)
        dt = time.perf_counter() - t0
        gbps = (arr.nbytes * n * 2) / dt / 1e9
        results[f"put_get_{mb}mb_gbps"] = gbps
        print(f"put+get {mb} MB: {gbps:.2f} GB/s round-trip")

    return results


if __name__ == "__main__":
    run_microbenchmark()
