"""Scheduler/object-plane microbenchmarks (reference: `ray microbenchmark`,
python/ray/_private/ray_perf.py:93-240 — same op families, re-measured for
this runtime).

Runs against a real local cluster (conductor + node daemon + shm store +
spawned workers — NOT local_mode) so the numbers include the full RPC,
lease, serialization and shm paths. Writes MICROBENCH_r{N}.json when
--round N is given, else prints to stdout.

Usage:
    JAX_PLATFORMS=cpu python microbench.py [--round 2] [--quick]
    python -m ray_tpu microbenchmark            # same suite via the CLI
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def timed(fn, *, min_time: float = 1.0, min_iters: int = 3):
    """Run fn() repeatedly until min_time elapsed; return (per_call_s, n)."""
    fn()  # warmup
    n, t0 = 0, time.perf_counter()
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if dt >= min_time and n >= min_iters:
            return dt / n, n


def settle(seconds: float = 1.0) -> None:
    """Quiesce between op families: let the previous phase's GC backlog
    (refcount flushes, batched deletes, pool refills) drain so each family
    measures its own steady state, not the tail of its predecessor — the
    reference's ray_perf.py likewise measures op families in isolation."""
    import gc
    gc.collect()
    time.sleep(seconds)


def compare_results(old: dict, new: dict, tolerance: float) -> list:
    """Regression gate over two result dicts (or whole output files —
    either shape is accepted). Compares every metric PRESENT IN BOTH whose
    name marks it higher-is-better (``*_per_sec`` / ``*_gb_per_sec`` rates
    and ``*_efficiency`` fractions); metrics only one side has are
    skipped, so the gate survives suite growth. Returns the list of
    (name, old, new, ratio) regressions where ``new < tolerance * old``."""
    old = old.get("results", old)
    new = new.get("results", new)
    bad = []
    for name in sorted(set(old) & set(new)):
        if not (name.endswith("_per_sec") or name.endswith("_gb_per_sec")
                or name.endswith("_efficiency")):
            continue
        o, n = old[name], new[name]
        if not o:
            continue  # zero/absent baseline: no meaningful ratio
        ratio = n / o
        status = "ok" if n >= tolerance * o else "REGRESSED"
        print(f"  {name:45s} {o:>12} -> {n:>12}  x{ratio:.2f}  {status}")
        if status == "REGRESSED":
            bad.append((name, o, n, ratio))
    return bad


def run_compare(old_path: str, new_path: str, tolerance: float) -> int:
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    print(f"compare: {old_path} -> {new_path} (tolerance {tolerance})")
    bad = compare_results(old, new, tolerance)
    if bad:
        print(f"{len(bad)} metric(s) below {tolerance}x of baseline")
        return 1
    print("no regressions")
    return 0


def main(argv=None) -> int:
    # CPU default only for the benchmark run itself (library importers of
    # this module must NOT have their jax platform silently forced).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--compare", nargs=2, metavar=("OLD.json", "NEW.json"),
                    help="regression gate: compare two result files and "
                    "exit nonzero if any shared rate metric fell below "
                    "--tolerance x the old value (no benchmarks are run)")
    ap.add_argument("--tolerance", type=float, default=0.8,
                    help="--compare pass threshold as a fraction of the "
                    "old value (default 0.8; benchmarks on shared hosts "
                    "need slack for scheduler noise)")
    args = ap.parse_args(argv)
    if args.compare:
        return run_compare(args.compare[0], args.compare[1], args.tolerance)

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster

    scale = 0.2 if args.quick else 1.0
    results: dict = {}

    # -- raw RPC framing: serialized vs pipelined frames --------------
    # Measured OUTSIDE the cluster so the number isolates the wire/frame
    # cost (one in-flight call per socket vs sequence-numbered frames).
    from ray_tpu.cluster.protocol import RpcClient, RpcServer

    class _Echo:
        def rpc_echo(self, x):
            return x

        def rpc_echo_1ms(self, x):
            # Stand-in for real service time (lock contention, disk,
            # downstream RPC). Pipelining only pays off when the server
            # does WORK per call — on a zero-latency loopback the extra
            # executor handoff makes pipelined frames slower, so the
            # headline comparison injects 1ms.
            time.sleep(0.001)
            return x

    srv = RpcServer(_Echo())
    cli = RpcClient(srv.address)
    n_rpc = 200

    def rpc_serial():
        for _ in range(n_rpc):
            cli.call("echo", x=1)

    per, _ = timed(rpc_serial, min_time=1.0 * scale)
    results["rpc_roundtrip_per_sec"] = round(n_rpc / per, 1)

    def rpc_serial_1ms():
        for _ in range(n_rpc):
            cli.call("echo_1ms", x=1)

    per, _ = timed(rpc_serial_1ms, min_time=1.0 * scale)
    results["rpc_roundtrip_1ms_per_sec"] = round(n_rpc / per, 1)

    def rpc_pipelined_1ms():
        for f in [cli.call_async("echo_1ms", x=1) for _ in range(n_rpc)]:
            f.result()

    per, _ = timed(rpc_pipelined_1ms, min_time=1.0 * scale)
    results["rpc_pipelined_1ms_per_sec"] = round(n_rpc / per, 1)
    cli.close()
    srv.stop()

    # -- correctness tooling (r15): both measured without a cluster ---
    # rtcheck full-package scan: the tier-1 self-check runs this every
    # suite invocation, so its wall time is a gated budget (<10s).
    from ray_tpu.devtools.rtcheck import run_tree

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def rtcheck_scan():
        run_tree([pkg_root])

    per, _ = timed(rtcheck_scan, min_time=1.0 * scale)
    results["rtcheck_full_tree_per_sec"] = round(1 / per, 2)

    # NamedLock with the sanitizer armed, uncontended: the overhead every
    # armed control-plane lock acquisition pays (held-stack push/pop).
    from ray_tpu import config as _config
    from ray_tpu.util import lockcheck

    lockcheck.reset()
    _config.set_override("lockcheck_enabled", True)
    try:
        bench_lock = lockcheck.named_lock("bench.uncontended")
        n_lock = 20000

        def lock_loop():
            for _ in range(n_lock):
                with bench_lock:
                    pass

        per, _ = timed(lock_loop, min_time=1.0 * scale)
    finally:
        _config.clear_override("lockcheck_enabled")
        lockcheck.reset()
    results["lock_uncontended_per_sec"] = round(n_lock / per, 1)

    # 1GB store: a realistic fraction of a TPU-host's RAM — the default
    # 256MB can hold only two 100MB bandwidth-test objects, so the loop
    # would measure spill I/O instead of the put path. 4 workers: enough
    # parallelism for the async families without drowning a small host in
    # context switches.
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4,
                                "object_store_bytes": 1 << 30})
    ray_tpu.init(address=c.address)
    try:
        # -- put/get small objects ------------------------------------
        def put_small():
            for _ in range(100):
                ray_tpu.put(b"x" * 1024)

        per, _ = timed(put_small, min_time=1.0 * scale)
        results["put_1kb_per_sec"] = round(100 / per, 1)

        settle()
        ref = ray_tpu.put(b"y" * 1024)

        def get_small():
            for _ in range(100):
                ray_tpu.get(ref)

        per, _ = timed(get_small, min_time=1.0 * scale)
        results["get_1kb_per_sec"] = round(100 / per, 1)

        # -- put/get bandwidth (100MB numpy, zero-copy shm path) ------
        settle()
        big = np.zeros(100 * 1024 * 1024, dtype=np.uint8)

        def put_big():
            ray_tpu.get(ray_tpu.put(big))

        per, _ = timed(put_big, min_time=2.0 * scale, min_iters=2)
        results["put_get_100mb_gb_per_sec"] = round(0.1 / per, 2)

        # -- task submit+get roundtrip --------------------------------
        settle()
        @ray_tpu.remote
        def nop():
            return None

        def task_roundtrip():
            ray_tpu.get(nop.remote())

        per, _ = timed(task_roundtrip, min_time=2.0 * scale)
        results["task_roundtrip_per_sec"] = round(1 / per, 1)

        # -- observability overhead (obs_overhead gate) ---------------
        # The same roundtrip with tracing + the event ring on: the
        # flight-recorder tax is ring appends and span buffering only
        # (all shipping is async), so this must stay within tolerance
        # of the plain rate under --compare. Also measured with the
        # ring disabled, pinning the cost of the enabled()-check path.
        from ray_tpu import config as _config
        settle()
        _config.set_override("tracing_enabled", True)

        def task_roundtrip_traced():
            ray_tpu.get(nop.remote())

        per, _ = timed(task_roundtrip_traced, min_time=2.0 * scale)
        results["task_roundtrip_traced_per_sec"] = round(1 / per, 1)
        _config.clear_override("tracing_enabled")

        settle()
        _config.set_override("events_enabled", False)
        per, _ = timed(task_roundtrip, min_time=2.0 * scale)
        results["task_roundtrip_events_off_per_sec"] = round(1 / per, 1)
        _config.clear_override("events_enabled")

        # -- inline-return roundtrip (reply-carried 1KiB payload) -----
        # Exercises the execution-plane fast path end to end: the result
        # rides the push reply, the caller's get() is served from the
        # inline cache, and the store seal happens off the critical path.
        payload = b"p" * 1024

        @ray_tpu.remote
        def echo(x):
            return x

        def task_roundtrip_inline():
            ray_tpu.get(echo.remote(payload))

        per, _ = timed(task_roundtrip_inline, min_time=2.0 * scale)
        results["task_roundtrip_inline_per_sec"] = round(1 / per, 1)

        # -- async task throughput (pipelined submissions) ------------
        n_tasks = int(1000 * scale) or 100

        def task_async():
            ray_tpu.get([nop.remote() for _ in range(n_tasks)])

        per, _ = timed(task_async, min_time=2.0 * scale, min_iters=2)
        results["tasks_async_per_sec"] = round(n_tasks / per, 1)

        # -- actor calls ----------------------------------------------
        settle()
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.x = 0

            def incr(self):
                self.x += 1
                return self.x

        a = Counter.remote()
        ray_tpu.get(a.incr.remote())

        def actor_sync():
            ray_tpu.get(a.incr.remote())

        per, _ = timed(actor_sync, min_time=2.0 * scale)
        results["actor_call_sync_per_sec"] = round(1 / per, 1)

        # -- inline actor call (1KiB reply-carried result) ------------
        @ray_tpu.remote
        class Echo:
            def echo(self, x):
                return x

        e = Echo.remote()
        ray_tpu.get(e.echo.remote(b""))

        def actor_call_inline():
            ray_tpu.get(e.echo.remote(payload))

        per, _ = timed(actor_call_inline, min_time=2.0 * scale)
        results["actor_call_inline_per_sec"] = round(1 / per, 1)
        ray_tpu.kill(e)

        n_calls = int(1000 * scale) or 100

        def actor_async():
            ray_tpu.get([a.incr.remote() for _ in range(n_calls)])

        per, _ = timed(actor_async, min_time=2.0 * scale, min_iters=2)
        results["actor_calls_async_per_sec"] = round(n_calls / per, 1)

        # -- DAG roundtrips: classic lazy execute vs compiled graph ---
        # Same 2-actor chain both ways. Classic pays two task submissions
        # plus an owner-side get per execute; the compiled plan pays one
        # input-channel write and one leaf-channel read (the resident
        # loops never touch the scheduler).
        settle()
        from ray_tpu.dag import InputNode

        @ray_tpu.remote
        class Stage:
            def step(self, x):
                return x + 1

        s1, s2 = Stage.bind(), Stage.bind()
        with InputNode() as inp:
            chain = s2.step.bind(s1.step.bind(inp))

        def dag_classic():
            assert chain.execute(1) == 3

        per, _ = timed(dag_classic, min_time=2.0 * scale)
        results["dag_classic_roundtrip_per_sec"] = round(1 / per, 1)

        cg = chain.experimental_compile(max_in_flight=8)
        assert ray_tpu.get(cg.execute(1), timeout=30) == 3  # warm

        def compiled_graph():
            assert ray_tpu.get(cg.execute(1), timeout=30) == 3

        per, _ = timed(compiled_graph, min_time=2.0 * scale)
        results["compiled_graph_roundtrip_per_sec"] = round(1 / per, 1)

        # -- r16: array value through the same compiled chain ---------
        # A 512KB float32 rides each channel hop as an RTAR slot
        # (FLAG_ARRAY): header + raw buffer, no pickle on either side.
        arr512 = np.zeros(128 * 1024, dtype=np.float32)
        assert ray_tpu.get(cg.execute(arr512),
                           timeout=30).nbytes == arr512.nbytes  # warm

        def compiled_graph_array():
            out = ray_tpu.get(cg.execute(arr512), timeout=30)
            assert out.nbytes == arr512.nbytes

        per, _ = timed(compiled_graph_array, min_time=2.0 * scale)
        results["channel_array_roundtrip_per_sec"] = round(1 / per, 1)
        cg.teardown()
        for s in (s1, s2):
            ray_tpu.kill(s._actor_handle)

        # -- MPMD pipeline schedules over cgraph channels (r13) -------
        # Three views of the same machinery: raw scheduled-step turnaround
        # with no compute (channel + program overhead), measured 1F1B
        # efficiency against the m/(m+s-1) bubble bound (sleep stages
        # overlap even on one core, so this gates the SCHEDULE, not the
        # host), and the speedup over running the identical per-microbatch
        # work as classic serial actor RPCs.
        settle()
        from ray_tpu.train.pipeline import CompiledPipeline, SleepStage

        PipeStage = ray_tpu.remote(SleepStage)

        # (a) zero-work scheduled-step roundtrip
        nul = [PipeStage.options(num_cpus=1).remote(0.0, 0.0)
               for _ in range(2)]
        ray_tpu.get([a.ping.remote() for a in nul])
        pipe = CompiledPipeline(nul, num_microbatches=4, schedule="1f1b")
        payload = [b"x" * 64] * 4
        pipe.step(payload)  # warm

        def pipeline_step_nul():
            pipe.step(payload)

        per, _ = timed(pipeline_step_nul, min_time=2.0 * scale)
        results["pipeline_stage_roundtrip_per_sec"] = round(1 / per, 1)
        pipe.teardown()
        for a in nul:
            ray_tpu.kill(a)

        # (b) measured 1F1B efficiency vs the bubble bound
        settle()
        fwd_s, bwd_s, s_pp, m_pp = 0.01, 0.02, 3, 6
        stages = [PipeStage.options(num_cpus=1).remote(fwd_s, bwd_s)
                  for _ in range(s_pp)]
        ray_tpu.get([a.ping.remote() for a in stages])
        pipe = CompiledPipeline(stages, num_microbatches=m_pp,
                                schedule="1f1b")
        payload = [b"x" * 64] * m_pp
        effs = []
        for i in range(5):
            r = pipe.step(payload)
            if i >= 1:              # step 0 has no inter-collect wall
                effs.append(r["efficiency"])
        effs.sort()
        results["pipeline_1f1b_efficiency"] = round(
            effs[len(effs) // 2], 4)
        results["pipeline_1f1b_bubble_bound"] = round(pipe.bound, 4)
        pipe.teardown()

        # (c) same per-microbatch work, serial classic RPCs (the DP/
        # sequential strawman: no microbatch overlap across stages)
        def dp_style_step():
            for _ in range(m_pp):
                for a in stages:
                    ray_tpu.get(a.pipe_forward.remote(0, 0, b"x"))
                for a in reversed(stages):
                    ray_tpu.get(a.pipe_backward.remote(0, 0, b"x"))

        per_dp, _ = timed(dp_style_step, min_time=2.0 * scale,
                          min_iters=2)
        # pipelined wall per step, steady state
        pipe2 = CompiledPipeline(stages, num_microbatches=m_pp,
                                 schedule="1f1b")
        pipe2.step(payload)
        walls = []
        for _ in range(3):
            walls.append(pipe2.step(payload)["wall_s"])
        pipe2.teardown()
        results["pipeline_vs_dp_step_speedup"] = round(
            per_dp / min(walls), 2)
        for a in stages:
            ray_tpu.kill(a)

        # -- actor creation throughput (zygote fork path) -------------
        # End-to-end: N actors created, first method call acked, killed.
        # Fractional CPUs so the 4-CPU cluster holds the whole cohort.
        settle()
        LightCounter = Counter.options(num_cpus=0.05)
        n_act = int(40 * scale) or 8

        def actor_create():
            actors = [LightCounter.remote() for _ in range(n_act)]
            ray_tpu.get([x.incr.remote() for x in actors])
            for x in actors:
                ray_tpu.kill(x)

        per, _ = timed(actor_create, min_time=2.0 * scale, min_iters=2)
        results["actor_creation_per_sec"] = round(n_act / per, 1)
        results["host_cpus"] = os.cpu_count()  # creation is CPU-bound:
        # fork + worker boot + RPCs parallelize across cores on real hosts

        # -- 100-actor wave (SCALE_r03 collapse scenario) -------------
        # One coalesced register_actors + one start_actors batch + shared
        # resolver long-poll; steady-state (recycled workers), like the
        # repeated-wave shape of real serving/training fan-outs.
        settle()
        WaveCounter = Counter.options(num_cpus=0.01)

        def actor_wave_100():
            actors = [WaveCounter.remote() for _ in range(100)]
            ray_tpu.get([x.incr.remote() for x in actors])
            for x in actors:
                ray_tpu.kill(x)

        per, _ = timed(actor_wave_100, min_time=2.0 * scale, min_iters=2)
        results["actor_creation_wave_100_per_sec"] = round(100 / per, 1)

        # -- wait over many refs --------------------------------------
        settle()
        refs = [ray_tpu.put(i) for i in range(1000)]

        def wait_1k():
            ray_tpu.wait(refs, num_returns=len(refs), timeout=30)

        per, _ = timed(wait_1k, min_time=1.0 * scale, min_iters=2)
        results["wait_1k_refs_per_sec"] = round(1 / per, 2)
        del refs

        # -- scheduler drain: queue 2k tasks at once ------------------
        settle()
        n_q = int(2000 * scale) or 200
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n_q)])
        results["queued_tasks_drained_per_sec"] = round(
            n_q / (time.perf_counter() - t0), 1)

        # -- serve ingress (r14): HTTP end-to-end, shed fast path, ----
        # -- adaptive vs fixed batching -------------------------------
        # End-to-end RPS through the proxy (admission + routing + replica
        # call), the cost of REJECTING at the admission gate (shedding
        # must stay cheap under overload or the gate itself melts), and
        # @serve.batch throughput with a fixed window vs the p99-target
        # adaptive window growing it under light latency pressure.
        settle()
        import urllib.request as _url
        from ray_tpu import serve as _serve

        # Fractional CPUs: the 4-CPU bench cluster still hosts earlier
        # families' actors; controller + proxy + 2 replicas must fit.
        @_serve.deployment(num_replicas=2, route_prefix="/bench",
                           max_ongoing_requests=16,
                           ray_actor_options={"num_cpus": 0.25})
        def bench_fn(x=0):
            return {"x": x}

        bh = _serve.run(bench_fn.bind(), http_host="127.0.0.1")
        bench_port = bh.http_port

        def http_once(i):
            req = _url.Request(
                f"http://127.0.0.1:{bench_port}/bench",
                data=json.dumps({"x": i}).encode(),
                headers={"Content-Type": "application/json"})
            return _url.urlopen(req, timeout=30).read()

        for i in range(10):
            http_once(i)   # warm routes cache + replica handles
        import concurrent.futures as _cf
        n_http = int(200 * scale) or 40
        pool8 = _cf.ThreadPoolExecutor(max_workers=8)

        def serve_http():
            list(pool8.map(http_once, range(n_http)))

        per, _ = timed(serve_http, min_time=1.0 * scale)
        results["serve_http_per_sec"] = round(n_http / per, 1)

        # Zero the queue budget IN THE PROXY PROCESS (a driver-local
        # set_override only reaches processes spawned afterwards) so
        # every request sheds at the admission gate.
        from ray_tpu.serve.api import _get_controller
        _ctrl = _get_controller(create=False)
        ray_tpu.get(_ctrl.http_reconfigure.remote(
            {"serve_max_queued_requests": 0}), timeout=30)

        def shed_once(i):
            try:
                http_once(i)
                return False
            except _url.HTTPError as e:
                return e.code == 503

        def serve_shed():
            assert all(pool8.map(shed_once, range(n_http)))

        per, _ = timed(serve_shed, min_time=1.0 * scale)
        results["serve_shed_per_sec"] = round(n_http / per, 1)
        ray_tpu.get(_ctrl.http_reconfigure.remote(
            {"serve_max_queued_requests": None}), timeout=30)
        pool8.shutdown()
        _serve.shutdown()   # frees the replicas' CPUs for later families

        def bench_batch(deco):
            @deco
            def work(items):
                time.sleep(0.002)  # per-flush cost batching amortizes
                return list(items)

            n_b = int(400 * scale) or 80
            with _cf.ThreadPoolExecutor(max_workers=16) as ex:
                t0 = time.perf_counter()
                list(ex.map(work, range(n_b)))
                return n_b / (time.perf_counter() - t0)

        results["serve_batch_fixed_per_sec"] = round(bench_batch(
            _serve.batch(max_batch_size=32,
                         batch_wait_timeout_s=0.005)), 1)
        results["serve_batch_adaptive_per_sec"] = round(bench_batch(
            _serve.batch(max_batch_size=32, batch_wait_timeout_s=0.005,
                         target_p99_ms=50.0)), 1)

        # -- node-to-node pull bandwidth (100MB) ----------------------
        # LAST: these add peer nodes, which would change the placement
        # topology the families above are measured on.
        # A second node's ObjectPlane pulls a head-held object into its
        # own store: the full probe + windowed multi-chunk transfer path.
        # Reported twice: the default config (same-host daemons take the
        # shm-direct segment copy) and the chunked-TCP path that
        # cross-host pulls use (object_pull_shm_direct off).
        settle()
        from ray_tpu import config
        from ray_tpu.core import api as core_api
        from ray_tpu.cluster.object_plane import ObjectPlane

        rt = core_api._runtime
        peers = [c.add_node(num_cpus=1, object_store_bytes=512 << 20)
                 for _ in range(4)]
        c.wait_for_nodes(5)
        planes = [ObjectPlane(n.store, n.node_id, c.address,
                              daemon_address=n.address)
                  for n in peers]

        def pull_100mb_best() -> float:
            times = []
            for _ in range(5):
                ref = ray_tpu.put(big)
                key = rt.plane._key(ref.id)
                t0 = time.perf_counter()
                out = planes[0]._pull(key, rt.daemon_address)
                times.append(time.perf_counter() - t0)
                assert out == "ok", out
                peers[0].store.delete(key)
                del ref
            return min(times)

        results["pull_remote_100mb_gb_per_sec"] = round(
            0.1 / pull_100mb_best(), 2)
        config.set_override("object_pull_shm_direct", False)
        results["pull_remote_100mb_tcp_gb_per_sec"] = round(
            0.1 / pull_100mb_best(), 2)
        config.clear_override("object_pull_shm_direct")
        # Serial chunk loop measured on this host immediately before the
        # windowed/striped/direct rebuild — the r08 acceptance baseline.
        results["pull_remote_100mb_serial_baseline_gb_per_sec"] = 0.45

        # -- 4-way broadcast (64MB to 4 nodes concurrently) -----------
        # Pullers locate via the directory; mid-transfer registration
        # lets late pullers read from early completers instead of all
        # four piling on the origin (implicit broadcast tree).
        settle()
        big64 = np.zeros(64 * 1024 * 1024, dtype=np.uint8)

        def bcast_64mb():
            import threading as _threading
            ref = ray_tpu.put(big64)
            views = [None] * len(planes)

            def one(i):
                views[i] = planes[i].get_view(ref.id, timeout=60)

            ts = [_threading.Thread(target=one, args=(i,),
                                    name=f"bench-pull-{i}", daemon=True)
                  for i in range(len(planes))]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            # views hold the serialized blob (header + buffer), so >= raw.
            assert all(v is not None and v.nbytes >= big64.nbytes
                       for v in views)
            key = rt.plane._key(ref.id)
            del views
            for n in peers:
                try:
                    n.store.delete(key)
                except Exception:
                    pass
            del ref
            return dt

        dt = min(bcast_64mb() for _ in range(3))
        results["broadcast_64mb_4way_gb_per_sec"] = round(
            len(planes) * 0.064 / dt, 2)

        # -- r16: device-native array plane ---------------------------
        # Same-host array put/get on the RTAR fast path (header + raw
        # buffer, single copy in, read-only view out) vs the classic
        # pickle-5 path measured back to back as the same-day control.
        settle()

        def array_put_get():
            out = ray_tpu.get(ray_tpu.put(big))
            assert out.nbytes == big.nbytes

        per, _ = timed(array_put_get, min_time=2.0 * scale, min_iters=2)
        results["array_put_get_100mb_gb_per_sec"] = round(0.1 / per, 2)
        config.set_override("array_zero_copy_enabled", False)
        per, _ = timed(array_put_get, min_time=2.0 * scale, min_iters=2)
        results["array_put_get_100mb_classic_gb_per_sec"] = round(
            0.1 / per, 2)
        config.clear_override("array_zero_copy_enabled")

        # Coordinated broadcast tree (ObjectPlane.broadcast_object) to
        # the same 4 peers the directory-driven broadcast above used:
        # rounds of tree legs, each fresh holder serving the next wave.
        settle()

        def device_bcast() -> float:
            ref = ray_tpu.put(big64)
            members = [{"node_id": n.node_id, "address": n.address}
                       for n in peers]
            t0 = time.perf_counter()
            res = rt.plane.broadcast_object(ref.id, members)
            dt_ = time.perf_counter() - t0
            assert len(res["ok"]) + len(res["fallback"]) == len(peers), res
            key = rt.plane._key(ref.id)
            for n in peers:
                try:
                    n.store.delete(key)
                except Exception:
                    pass
            del ref
            return dt_

        dt = min(device_bcast() for _ in range(3))
        results["device_broadcast_64mb_4way_gb_per_sec"] = round(
            len(peers) * 0.064 / dt, 2)

        # -- object tiering: coordinated spill + restore (r12) --------
        # One 100MB primary is written through the node daemon's spill
        # backend, evicted from shm, and restored by the driver plane's
        # third-tier get — the full durable-copy round trip
        # (local_object_manager.h's spill and restore halves).
        settle()
        from ray_tpu.cluster.protocol import get_client as _get_client
        daemon_cli = _get_client(rt.daemon_address)

        def spill_restore_100mb() -> float:
            ref = ray_tpu.put(big)
            key = rt.plane._key(ref.id)
            t0 = time.perf_counter()
            freed = daemon_cli.call("spill_request",
                                    want_bytes=1 << 40)["freed"]
            assert freed >= big.nbytes, f"spill only freed {freed}"
            view = rt.plane.get_view(ref.id, timeout=120)
            dt = time.perf_counter() - t0
            assert view.nbytes >= big.nbytes
            del view
            daemon_cli.call("delete_object", oid=key)
            del ref
            return dt

        n_sr = 2 if args.quick else 4
        dt = min(spill_restore_100mb() for _ in range(n_sr))
        results["spill_restore_100mb_gb_per_sec"] = round(0.1 / dt, 2)

        # -- put throughput while overcommitted ------------------------
        # Sustained 100MB puts past store capacity: admission rides the
        # native LRU spill plus the daemon's coordinated spill manager
        # (put-side spill-then-admit backpressure instead of ST_OOM).
        settle()
        n_press = 4 if args.quick else 12
        t0 = time.perf_counter()
        press_refs = [ray_tpu.put(big) for _ in range(n_press)]
        dt = time.perf_counter() - t0
        results["put_under_pressure_gb_per_sec"] = round(
            n_press * 0.1 / dt, 2)
        del press_refs

    finally:
        ray_tpu.shutdown()
        c.shutdown()

    out = {
        "suite": "ray_tpu microbenchmark",
        "reference_analog": "python/ray/_private/ray_perf.py:93",
        "mode": "cluster (conductor+daemon+shm store+spawned workers)",
        "results": results,
    }
    line = json.dumps(out, indent=2)
    if args.round:
        path = f"MICROBENCH_r{args.round:02d}.json"
        with open(path, "w") as f:
            f.write(line + "\n")
        print(f"wrote {path}")
    print(line)
    return 0


def run_microbenchmark(address=None) -> int:
    """CLI entry (`python -m ray_tpu microbenchmark`): run the full suite.
    The suite OWNS its cluster so numbers are comparable run-to-run; a
    live-cluster --address is therefore rejected, not silently ignored."""
    if address:
        raise SystemExit(
            "microbenchmark always measures a fresh local cluster for "
            "comparable numbers; drop --address")
    return main([])


if __name__ == "__main__":
    sys.exit(main())
