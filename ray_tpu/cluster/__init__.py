"""Distributed cluster runtime.

Process anatomy (role parity in parentheses, per SURVEY.md §1):
  conductor    — cluster control plane (GCS, src/ray/gcs): node/actor/PG/job
                 tables, KV, named actors, pubsub, health checks.
  node daemon  — per-node manager (raylet, src/ray/raylet): worker pool,
                 local lease scheduler, object-store supervision, spillback.
  shmstored    — C++ shared-memory object store (plasma), native/shmstore/.
  workers      — task/actor executor processes (core worker + default_worker).

Control RPCs are msgpack-framed asyncio TCP (protocol.py); bulk objects move
through shared memory locally and chunked TCP between nodes (transfer in the
node daemon).
"""
