"""Sender-initiated object push.

Role parity: src/ray/object_manager/push_manager.h — when the owner learns a
task's destination node, it proactively streams the task's argument objects
there instead of waiting for the destination worker to discover and pull
them (saves the locate round-trip and overlaps transfer with worker
checkout). Push is best-effort: the destination's pull path remains the
correctness backstop, so any push failure is simply dropped.

Dedup and flow control follow the reference: one in-flight push per
(object, destination), a recently-pushed TTL cache so hot args aren't
re-sent to the same node, and a global in-flight byte cap (push_manager.h
chunk window role).
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Tuple

from ray_tpu.cluster import fault_plane
from ray_tpu.cluster.protocol import get_client
from ray_tpu.util import events as _events

PUSH_CHUNK = 1 << 20          # bytes per push_chunk RPC
_RECENT_TTL_S = 30.0          # don't re-push same (oid, target) within this
_MAX_INFLIGHT_BYTES = 256 << 20


class PushManager:
    def __init__(self, store, self_daemon_address: str):
        self.store = store
        self.self_daemon = self_daemon_address
        self._inflight: Dict[Tuple[bytes, str], float] = {}
        self._recent: Dict[Tuple[bytes, str], float] = {}
        self._lock = threading.Lock()
        self._bytes = 0
        self._pool = ThreadPoolExecutor(max_workers=4,
                                        thread_name_prefix="obj-push")

    def maybe_push(self, key: bytes, target_daemon: str) -> bool:
        """Queue a best-effort push of a LOCAL object to target_daemon.
        Returns True if a push was scheduled."""
        if target_daemon == self.self_daemon:
            return False
        ident = (key, target_daemon)
        now = time.monotonic()
        with self._lock:
            if ident in self._inflight:
                return False
            ts = self._recent.get(ident)
            if ts is not None and now - ts < _RECENT_TTL_S:
                return False
            if self._bytes >= _MAX_INFLIGHT_BYTES:
                return False  # saturated: destination pull is the backstop
            self._inflight[ident] = now
            if len(self._recent) > 4096:
                cutoff = now - _RECENT_TTL_S
                self._recent = {k: v for k, v in self._recent.items()
                                if v > cutoff}
        self._pool.submit(self._push, ident)
        return True

    def _push(self, ident: Tuple[bytes, str]) -> None:
        key, target = ident
        admitted = 0
        try:
            view = self.store.get(key, timeout=0.0)
            if view is None:
                return  # not local (or evicted since) — nothing to push
            try:
                size = view.nbytes
                with self._lock:
                    self._bytes += size
                admitted = size
                from ray_tpu import config
                cli = get_client(target)
                # Per-push stream id: lets the receiver tell this push's
                # chunks apart from a competing sender's (node_daemon
                # rpc_push_chunk rejects cross-stream chunks instead of
                # destroying the in-progress entry).
                import os as _os
                stream = _os.urandom(8).hex()
                # Windowed pipelined sends (push_manager.h chunk window):
                # keep object_push_window chunk RPCs in flight on one
                # channel; the receiver accepts out-of-order offsets within
                # a stream. PickleBuffer chunks ride the RPC frame's
                # out-of-band path — sent straight from the shm mapping,
                # never copied into a bytes().
                window = max(1, int(config.get("object_push_window")))
                futs: deque = deque()

                def _acked_terminal() -> bool:
                    # Bounded per-chunk wait: a hung destination must not
                    # pin this pool thread / the in-flight byte budget.
                    resp = futs.popleft().result(timeout=30.0)
                    # done/reject: destination has it / is pulling it.
                    return bool(resp.get("done") or resp.get("reject"))

                done = False
                off = 0
                while off < size and not done:
                    n = min(PUSH_CHUNK, size - off)
                    act = fault_plane.fire("object.push.chunk", oid=key,
                                           offset=off, target=target)
                    if act == "sever":
                        cli.sever_pipe()
                    _events.emit("push.chunk", key.hex(), value=float(n),
                                 attrs={"target": target})
                    futs.append(cli.call_async(
                        "push_chunk", oid=key, offset=off, total=size,
                        chunk=pickle.PickleBuffer(view[off:off + n]),
                        stream=stream))
                    off += n
                    while len(futs) >= window and not done:
                        done = _acked_terminal()
                while futs and not done:
                    done = _acked_terminal()
            finally:
                self.store.release(key)
        except Exception:
            pass  # best-effort: destination pull path covers it
        finally:
            with self._lock:
                if admitted:
                    self._bytes -= admitted
                self._inflight.pop(ident, None)
                self._recent[ident] = time.monotonic()

    def stats(self) -> dict:
        with self._lock:
            return {"inflight": len(self._inflight),
                    "inflight_bytes": self._bytes,
                    "recent": len(self._recent)}
