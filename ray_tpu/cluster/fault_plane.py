"""Deterministic, seeded fault-injection plane.

Role parity: the reference's chaos hooks (RAY_testing_asio_delay_us,
ray_config_def.h:762, plus the kill-raylet/kill-gcs helpers its
test_chaos/test_failure suites script by hand). Here the hooks are
first-class: every plane exposes named fault points —

    fault_plane.fire("rpc.server.dispatch", method=method)

— and a config-driven PLAN decides which points fire, when, and how.
The plan is a JSON list of rules in the ``fault_plan`` flag, so it
propagates to spawned daemons and workers like any other system-config
override (RT_SYSTEM_CONFIG_JSON), letting one test script faults deep
inside child processes.

Rule shape (all keys optional except ``site``)::

    {"site": "rpc.server.reply",      # exact name or fnmatch pattern
     "match": {"method": "fetch_chunk"},  # equality filters on fire() ctx
     "action": "delay",               # delay|raise|drop_reply|sever|crash
     "delay_s": 0.2,                  # for delay
     "exc": "ConnectionLost",         # for raise (exception class name)
     "nth": 3,                        # fire on the 3rd matching hit only
     "every": 2,                      # or: fire every 2nd matching hit
     "prob": 0.1, "seed": 7,          # or: seeded per-hit probability
     "times": 1}                      # max firings (default: unlimited)

Scheduling is deterministic: nth/every count matching hits per rule in
this process; probability rules draw from ``random.Random`` seeded with
``seed ^ crc32(site)`` (falling back to the ``fault_seed`` flag), so the
same plan + same hit sequence reproduces the same faults. Chaos tests
print their seed so a failure replays exactly.

Action contract at a fault point:

- ``delay``  — handled here (sleep), fire() returns None.
- ``raise``  — raises the named exception from fire().
- ``crash``  — ``os._exit(exit_code)`` (default 17): a hard process kill
  with no atexit/finally, the closest stand-in for SIGKILL/preemption.
- ``drop_reply`` / ``sever`` — returned as a string; only call sites
  that can honor them (server reply path, client socket paths) check
  the return value, everywhere else they are ignored.

Disabled cost: fire() compares one cached generation int and does one
dict lookup, then returns — no config re-resolution, no allocation —
so fault points stay free on the hot RPC/dispatch paths when no plan
is loaded. The legacy ``testing_rpc_delay_us`` flag is subsumed: it is
compiled into delay rules on the ``rpc.server.dispatch`` site.

Object-tiering sites (spill/restore/evict, r12): ``object.spill.write``
fires before the daemon writes a cold primary through the spill backend
(raise = the write fails, the shm copy stays); ``object.spill.restore``
fires before a plane restores from a spill URL and before a daemon
serves a chunk from its spill file (delay models slow backends, raise
drives the restore-failure -> remove_spilled -> reconstruction path);
``object.evict`` fires before the shm copy of a spilled object is
dropped (raise keeps dual copies — safe, the durable copy already
exists).

Serve ingress sites (r14): ``serve.proxy.admit`` fires in the HTTP
proxy before a request is admitted (raise = shed with 503, the
admission-rejection chaos knob); ``serve.replica.call`` fires inside
the replica before user code runs (crash kills the replica mid-request
— the headline chaos-SLO scenario; the handle retries the call on
another replica); ``serve.replica.drain`` fires when the controller
marks a replica DRAINING (raise degrades the graceful drain to an
immediate kill). Replacement replicas re-arm per-process hit counters,
so ``nth``-scheduled kills recur across respawns.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from ray_tpu import config


class FaultInjected(Exception):
    """Default exception raised by a ``raise`` action."""


def _exc_class(name: str):
    if name in ("ConnectionLost", "RpcError"):
        from ray_tpu.cluster import protocol
        return getattr(protocol, name)
    return {
        "OSError": OSError,
        "ConnectionError": ConnectionError,
        "ConnectionResetError": ConnectionResetError,
        "BrokenPipeError": BrokenPipeError,
        "TimeoutError": TimeoutError,
        "RuntimeError": RuntimeError,
    }.get(name, FaultInjected)


class _Rule:
    __slots__ = ("site", "match", "action", "delay_s", "exc", "nth",
                 "every", "prob", "times", "rng", "hits", "fired", "key")

    def __init__(self, spec: Dict[str, Any], index: int, base_seed: int):
        self.site = spec["site"]
        self.match = spec.get("match") or {}
        self.action = spec.get("action", "raise")
        self.delay_s = float(spec.get("delay_s", 0.0))
        self.exc = spec.get("exc", "FaultInjected")
        self.nth = spec.get("nth")
        self.every = spec.get("every")
        self.prob = spec.get("prob")
        self.times = spec.get("times")
        seed = spec.get("seed", base_seed)
        self.rng = random.Random(
            int(seed) ^ zlib.crc32(self.site.encode()) ^ index)
        self.hits = 0
        self.fired = 0
        # Identity that survives plan recompiles (a config generation bump
        # from an unrelated set_override must not reset nth-hit counters).
        self.key = (index, json.dumps(spec, sort_keys=True))

    def adopt(self, prev: "_Rule") -> None:
        self.hits, self.fired, self.rng = prev.hits, prev.fired, prev.rng

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        for k, v in self.match.items():
            if ctx.get(k) != v:
                return False
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            hit = self.hits == int(self.nth)
        elif self.every is not None:
            hit = self.hits % int(self.every) == 0
        elif self.prob is not None:
            hit = self.rng.random() < float(self.prob)
        else:
            hit = True
        if hit:
            self.fired += 1
        return hit


class _Compiled:
    __slots__ = ("gen", "exact", "patterns", "legacy")

    def __init__(self, gen: int):
        self.gen = gen
        self.exact: Dict[str, List[_Rule]] = {}
        self.patterns: List[_Rule] = []
        self.legacy: Optional[str] = None  # testing_rpc_delay_us spec


_compiled = _Compiled(-1)
_lock = threading.Lock()
_stats: Dict[str, int] = {}


def _recompile() -> _Compiled:
    global _compiled
    with _lock:
        if _compiled.gen == config.generation:
            return _compiled
        prev = {}
        for rules in list(_compiled.exact.values()) + [_compiled.patterns]:
            for r in rules:
                prev[r.key] = r
        new = _Compiled(config.generation)
        blob = config.get("fault_plan")
        base_seed = int(config.get("fault_seed"))
        specs = json.loads(blob) if blob else []
        for i, spec in enumerate(specs):
            rule = _Rule(spec, i, base_seed)
            if rule.key in prev:
                rule.adopt(prev[rule.key])
            if any(c in rule.site for c in "*?["):
                new.patterns.append(rule)
            else:
                new.exact.setdefault(rule.site, []).append(rule)
        legacy = config.get("testing_rpc_delay_us")
        new.legacy = str(legacy) if legacy else None
        _compiled = new
        return new


def _legacy_delay(spec: str, method: str) -> None:
    # testing_rpc_delay_us compatibility: "<us>" or "<method>:<us>,..."
    if ":" in spec:
        for part in spec.split(","):
            name, _, us = part.partition(":")
            if name == method and us.isdigit():
                time.sleep(int(us) / 1e6)
                return
    elif spec.isdigit() and int(spec):
        time.sleep(int(spec) / 1e6)


def fire(site: str, **ctx: Any) -> Optional[str]:
    """Evaluate one fault point. Returns None (possibly after sleeping),
    returns "drop_reply"/"sever" for the call site to honor, raises the
    rule's exception, or never returns (crash)."""
    c = _compiled
    if c.gen != config.generation:
        c = _recompile()
    rules = c.exact.get(site)
    if rules is None and not c.patterns and c.legacy is None:
        return None  # disabled fast path
    if c.legacy is not None and site == "rpc.server.dispatch":
        _legacy_delay(c.legacy, ctx.get("method", ""))
    out: Optional[str] = None
    matched = list(rules) if rules else []
    for r in c.patterns:
        if fnmatch.fnmatch(site, r.site):
            matched.append(r)
    for r in matched:
        with _lock:
            hit = r.should_fire(ctx)
        if not hit:
            continue
        _stats[site] = _stats.get(site, 0) + 1
        try:
            # Lazy import: fault_plane loads before the util package in
            # some spawn paths, and a fired rule is far off any hot path.
            from ray_tpu.util import events as _events
            _events.emit("fault.fired", site, attrs={"action": r.action})
        except Exception:
            pass
        if r.action == "delay":
            time.sleep(r.delay_s)
        elif r.action == "raise":
            raise _exc_class(r.exc)(
                f"injected fault at {site} ({ctx or {}})")
        elif r.action == "crash":
            os._exit(17)
        elif r.action in ("drop_reply", "sever"):
            out = r.action
    return out


def load_plan(rules: List[Dict[str, Any]], seed: int = 0) -> None:
    """Install a plan for this process AND (via config propagation) every
    daemon/worker spawned afterwards."""
    config.set_override("fault_plan", json.dumps(rules))
    config.set_override("fault_seed", int(seed))


def clear_plan() -> None:
    config.clear_override("fault_plan")
    config.clear_override("fault_seed")
    reset()


def reset() -> None:
    """Forget hit counters and stats (plan rules re-arm)."""
    global _compiled
    with _lock:
        _compiled = _Compiled(-1)
        _stats.clear()


def stats() -> Dict[str, int]:
    """Fired-count per site in this process (test assertions)."""
    with _lock:
        return dict(_stats)
