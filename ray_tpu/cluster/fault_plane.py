"""Deterministic, seeded fault-injection plane.

Role parity: the reference's chaos hooks (RAY_testing_asio_delay_us,
ray_config_def.h:762, plus the kill-raylet/kill-gcs helpers its
test_chaos/test_failure suites script by hand). Here the hooks are
first-class: every plane exposes named fault points —

    fault_plane.fire("rpc.server.dispatch", method=method)

— and a config-driven PLAN decides which points fire, when, and how.
The plan is a JSON list of rules in the ``fault_plan`` flag, so it
propagates to spawned daemons and workers like any other system-config
override (RT_SYSTEM_CONFIG_JSON), letting one test script faults deep
inside child processes.

Rule shape (all keys optional except ``site``)::

    {"site": "rpc.server.reply",      # exact name or fnmatch pattern
     "match": {"method": "fetch_chunk"},  # equality filters on fire() ctx
     "action": "delay",               # delay|raise|drop_reply|sever|crash
     "delay_s": 0.2,                  # for delay
     "exc": "ConnectionLost",         # for raise (exception class name)
     "nth": 3,                        # fire on the 3rd matching hit only
     "every": 2,                      # or: fire every 2nd matching hit
     "prob": 0.1, "seed": 7,          # or: seeded per-hit probability
     "times": 1}                      # max firings (default: unlimited)

Scheduling is deterministic: nth/every count matching hits per rule in
this process; probability rules draw from ``random.Random`` seeded with
``seed ^ crc32(site)`` (falling back to the ``fault_seed`` flag), so the
same plan + same hit sequence reproduces the same faults. Chaos tests
print their seed so a failure replays exactly.

Action contract at a fault point:

- ``delay``  — handled here (sleep), fire() returns None.
- ``raise``  — raises the named exception from fire().
- ``crash``  — ``os._exit(exit_code)`` (default 17): a hard process kill
  with no atexit/finally, the closest stand-in for SIGKILL/preemption.
- ``drop_reply`` / ``sever`` — returned as a string; only call sites
  that can honor them (server reply path, client socket paths) check
  the return value, everywhere else they are ignored.

Disabled cost: fire() compares one cached generation int and does one
dict lookup, then returns — no config re-resolution, no allocation —
so fault points stay free on the hot RPC/dispatch paths when no plan
is loaded. The legacy ``testing_rpc_delay_us`` flag is subsumed: it is
compiled into delay rules on the ``rpc.server.dispatch`` site.

Object-tiering sites (spill/restore/evict, r12): ``object.spill.write``
fires before the daemon writes a cold primary through the spill backend
(raise = the write fails, the shm copy stays); ``object.spill.restore``
fires before a plane restores from a spill URL and before a daemon
serves a chunk from its spill file (delay models slow backends, raise
drives the restore-failure -> remove_spilled -> reconstruction path);
``object.evict`` fires before the shm copy of a spilled object is
dropped (raise keeps dual copies — safe, the durable copy already
exists).

Serve ingress sites (r14): ``serve.proxy.admit`` fires in the HTTP
proxy before a request is admitted (raise = shed with 503, the
admission-rejection chaos knob); ``serve.replica.call`` fires inside
the replica before user code runs (crash kills the replica mid-request
— the headline chaos-SLO scenario; the handle retries the call on
another replica); ``serve.replica.drain`` fires when the controller
marks a replica DRAINING (raise degrades the graceful drain to an
immediate kill). Replacement replicas re-arm per-process hit counters,
so ``nth``-scheduled kills recur across respawns.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from ray_tpu import config


# Canonical fault-site registry: every ``fire("…")`` literal in the tree
# must be listed here (enforced by rtcheck's fault-sites checker, both
# directions), ``load_plan`` validates rule sites against it, and the
# ``ray_tpu fault-sites`` CLI prints it. The one-line doc says where the
# site sits and what a fired rule models.
SITES: Dict[str, str] = {
    "rpc.server.dispatch": "server, before a handler runs (delay models "
                           "a slow/overloaded server; subsumes "
                           "testing_rpc_delay_us)",
    "rpc.server.reply": "server, before the reply frame is written "
                        "(drop_reply models a reply lost on the wire)",
    "rpc.client.send": "client, before a request frame is written "
                       "(sever cuts the connection mid-send)",
    "rpc.client.recv": "client, while waiting for a reply frame "
                       "(raise ConnectionLost models a dead peer)",
    "conductor.journal.append": "conductor, before a journal record is "
                                "appended (raise models journal-disk "
                                "failure)",
    "conductor.actor.schedule": "conductor, before an actor placement "
                                "decision commits",
    "conductor.location.add": "conductor, before an object location is "
                              "recorded in the directory",
    "daemon.worker.spawn": "daemon, before a worker process is forked "
                           "(raise models spawn failure / OOM-killer)",
    "daemon.lease.grant": "daemon, before a worker lease is granted",
    "daemon.chunk.serve": "daemon, before a pull chunk is served from "
                          "the local store",
    "object.pull": "object plane, at pull start (raise fails the pull "
                   "before any source is tried)",
    "object.pull.window": "object plane, per pull window grant (delay "
                          "models a saturated pull budget)",
    "object.pull.chunk": "object plane, per fetched chunk (raise drives "
                         "the source-failover path)",
    "object.push.chunk": "push manager, per pushed chunk (raise models "
                         "a failed push leg)",
    "object.spill.write": "daemon, before a cold primary is written to "
                          "the spill backend (raise keeps the shm copy)",
    "object.spill.restore": "plane/daemon, before a spilled object is "
                            "restored or served from its spill file "
                            "(raise drives reconstruction)",
    "object.evict": "daemon, before the shm copy of a spilled object is "
                    "dropped (raise keeps dual copies)",
    "worker.task.exec": "worker, before user task code runs (crash "
                        "models mid-task preemption)",
    "worker.actor.exec": "worker, before an actor method body runs",
    "task.return.seal": "worker, before a task return is sealed into "
                        "the store",
    "task.reply.inline": "worker, before an inline (small) return rides "
                         "the reply frame",
    "cgraph.channel.write": "compiled graph, before a shm channel slot "
                            "write",
    "cgraph.loop.crash": "compiled graph, inside the per-actor exec "
                         "loop (crash kills the pinned worker)",
    "serve.proxy.admit": "HTTP proxy, before a request is admitted "
                         "(raise sheds with 503)",
    "serve.replica.call": "replica, before user handler code runs "
                          "(crash is the chaos-SLO headline scenario)",
    "serve.replica.drain": "controller, when a replica is marked "
                           "DRAINING (raise degrades to immediate kill)",
    "object.array.export": "serialization, before an array buffer is "
                           "exported zero-copy (raise falls back to the "
                           "classic pickle path)",
    "object.collective.bcast": "object plane, per broadcast tree leg "
                               "(sever cuts that member's connection; "
                               "the member re-stripes onto the classic "
                               "pull path)",
}


class FaultInjected(Exception):
    """Default exception raised by a ``raise`` action."""


def _exc_class(name: str):
    if name in ("ConnectionLost", "RpcError"):
        from ray_tpu.cluster import protocol
        return getattr(protocol, name)
    return {
        "OSError": OSError,
        "ConnectionError": ConnectionError,
        "ConnectionResetError": ConnectionResetError,
        "BrokenPipeError": BrokenPipeError,
        "TimeoutError": TimeoutError,
        "RuntimeError": RuntimeError,
    }.get(name, FaultInjected)


class _Rule:
    __slots__ = ("site", "match", "action", "delay_s", "exc", "nth",
                 "every", "prob", "times", "rng", "hits", "fired", "key")

    def __init__(self, spec: Dict[str, Any], index: int, base_seed: int):
        self.site = spec["site"]
        self.match = spec.get("match") or {}
        self.action = spec.get("action", "raise")
        self.delay_s = float(spec.get("delay_s", 0.0))
        self.exc = spec.get("exc", "FaultInjected")
        self.nth = spec.get("nth")
        self.every = spec.get("every")
        self.prob = spec.get("prob")
        self.times = spec.get("times")
        seed = spec.get("seed", base_seed)
        self.rng = random.Random(
            int(seed) ^ zlib.crc32(self.site.encode()) ^ index)
        self.hits = 0
        self.fired = 0
        # Identity that survives plan recompiles (a config generation bump
        # from an unrelated set_override must not reset nth-hit counters).
        self.key = (index, json.dumps(spec, sort_keys=True))

    def adopt(self, prev: "_Rule") -> None:
        self.hits, self.fired, self.rng = prev.hits, prev.fired, prev.rng

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        for k, v in self.match.items():
            if ctx.get(k) != v:
                return False
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            hit = self.hits == int(self.nth)
        elif self.every is not None:
            hit = self.hits % int(self.every) == 0
        elif self.prob is not None:
            hit = self.rng.random() < float(self.prob)
        else:
            hit = True
        if hit:
            self.fired += 1
        return hit


class _Compiled:
    __slots__ = ("gen", "exact", "patterns", "legacy")

    def __init__(self, gen: int):
        self.gen = gen
        self.exact: Dict[str, List[_Rule]] = {}
        self.patterns: List[_Rule] = []
        self.legacy: Optional[str] = None  # testing_rpc_delay_us spec


_compiled = _Compiled(-1)
_lock = threading.Lock()
_stats: Dict[str, int] = {}


def _recompile() -> _Compiled:
    global _compiled
    with _lock:
        if _compiled.gen == config.generation:
            return _compiled
        prev = {}
        for rules in list(_compiled.exact.values()) + [_compiled.patterns]:
            for r in rules:
                prev[r.key] = r
        new = _Compiled(config.generation)
        blob = config.get("fault_plan")
        base_seed = int(config.get("fault_seed"))
        specs = json.loads(blob) if blob else []
        for i, spec in enumerate(specs):
            rule = _Rule(spec, i, base_seed)
            if rule.key in prev:
                rule.adopt(prev[rule.key])
            if any(c in rule.site for c in "*?["):
                new.patterns.append(rule)
            else:
                new.exact.setdefault(rule.site, []).append(rule)
        legacy = config.get("testing_rpc_delay_us")
        new.legacy = str(legacy) if legacy else None
        _compiled = new
        return new


def _legacy_delay(spec: str, method: str) -> None:
    # testing_rpc_delay_us compatibility: "<us>" or "<method>:<us>,..."
    if ":" in spec:
        for part in spec.split(","):
            name, _, us = part.partition(":")
            if name == method and us.isdigit():
                time.sleep(int(us) / 1e6)
                return
    elif spec.isdigit() and int(spec):
        time.sleep(int(spec) / 1e6)


def fire(site: str, **ctx: Any) -> Optional[str]:
    """Evaluate one fault point. Returns None (possibly after sleeping),
    returns "drop_reply"/"sever" for the call site to honor, raises the
    rule's exception, or never returns (crash)."""
    c = _compiled
    if c.gen != config.generation:
        c = _recompile()
    rules = c.exact.get(site)
    if rules is None and not c.patterns and c.legacy is None:
        return None  # disabled fast path
    if c.legacy is not None and site == "rpc.server.dispatch":
        _legacy_delay(c.legacy, ctx.get("method", ""))
    out: Optional[str] = None
    matched = list(rules) if rules else []
    for r in c.patterns:
        if fnmatch.fnmatch(site, r.site):
            matched.append(r)
    for r in matched:
        with _lock:
            hit = r.should_fire(ctx)
        if not hit:
            continue
        _stats[site] = _stats.get(site, 0) + 1
        try:
            # Lazy import: fault_plane loads before the util package in
            # some spawn paths, and a fired rule is far off any hot path.
            from ray_tpu.util import events as _events
            _events.emit("fault.fired", site, attrs={"action": r.action})
        except Exception:
            pass
        if r.action == "delay":
            time.sleep(r.delay_s)
        elif r.action == "raise":
            raise _exc_class(r.exc)(
                f"injected fault at {site} ({ctx or {}})")
        elif r.action == "crash":
            os._exit(17)
        elif r.action in ("drop_reply", "sever"):
            out = r.action
    return out


def load_plan(rules: List[Dict[str, Any]], seed: int = 0) -> None:
    """Install a plan for this process AND (via config propagation) every
    daemon/worker spawned afterwards. Rule sites must name a registered
    fault point (exact match against ``SITES``, or an fnmatch pattern
    matching at least one) — a typo'd site would otherwise arm a plan
    that silently never fires. The ``unit.`` prefix is reserved for
    tests that exercise the schedule machinery against synthetic
    ``fire()`` calls."""
    for spec in rules:
        site = spec.get("site", "")
        if site.startswith("unit."):
            continue
        if any(c in site for c in "*?["):
            if not any(fnmatch.fnmatch(s, site) for s in SITES):
                raise ValueError(
                    f"fault_plan pattern {site!r} matches no registered "
                    f"site (see fault_plane.SITES)")
        elif site not in SITES:
            raise ValueError(
                f"fault_plan site {site!r} is not registered in "
                f"fault_plane.SITES")
    config.set_override("fault_plan", json.dumps(rules))
    config.set_override("fault_seed", int(seed))


def clear_plan() -> None:
    config.clear_override("fault_plan")
    config.clear_override("fault_seed")
    reset()


def reset() -> None:
    """Forget hit counters and stats (plan rules re-arm)."""
    global _compiled
    with _lock:
        _compiled = _Compiled(-1)
        _stats.clear()


def stats() -> Dict[str, int]:
    """Fired-count per site in this process (test assertions)."""
    with _lock:
        return dict(_stats)
