"""Worker zygote: pre-import the worker stack once, fork workers on demand.

Role parity: the reference's worker pool keeps actor/task latency down by
prestarting and caching worker PROCESSES (worker_pool.h:156); the cost it
cannot amortize is the interpreter+import price of each cold start. On a
TPU host the Python worker stack costs ~0.25s to import — at that price a
burst of N actor creations serializes into N×0.25s of pure CPU. The zygote
pays the import once, then ``fork()`` produces a ready worker in ~15ms.

Protocol (newline-framed JSON over a unix socket, one request per
connection): {"argv": [...], "env": {...}, "cwd": null|str, "log": path}
-> {"pid": N}. The daemon treats a forked worker exactly like a spawned
one (same --token registration handshake); if the zygote is unavailable it
falls back to subprocess spawn.

Fork discipline: the zygote imports the worker modules but creates no
RPC clients or store connections (verified: importing worker_main starts
no threads), so the child inherits only clean module state. Its one
thread — the parent-death watchdog — holds no locks at any point, so
forking around it is safe (the thread simply doesn't exist in the child). The child closes the listener + request sockets, applies the
request env/cwd, redirects stdout/stderr to the worker log, and enters
``worker_main.main()``. SIGCHLD is ignored so exited workers are reaped by
the kernel (the daemon supervises worker liveness itself, by pid).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time


def _parent_watchdog(sock_path: str) -> None:
    """Exit when the spawning node daemon dies (SIGKILL included): a
    reparented zygote holds the imported worker stack (~150MB RSS) forever
    and nothing will ever ask it to fork again. getppid() flips to the
    reaper's pid on parent death — poll it (PR_SET_PDEATHSIG is
    thread-scoped in the parent and so unusable from a Popen'd child).
    Parity: worker-lifetime supervision, reference worker_pool.h:156."""
    ppid = os.getppid()
    while True:
        time.sleep(1.0)
        if os.getppid() != ppid:
            try:
                os.unlink(sock_path)
            except OSError:
                pass
            os._exit(0)  # rtcheck: allow-exit(orphaned zygote: parent daemon died, nothing to clean up)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    args = ap.parse_args()

    threading.Thread(target=_parent_watchdog, args=(args.socket,),
                     daemon=True, name="parent-watchdog").start()

    # Pay the import cost ONCE, before accepting fork requests — including
    # the modules worker_main.main() imports lazily (runtime_cluster/api
    # alone are ~75ms; leaving them to the child would erase most of the
    # fork win).
    import ray_tpu.core.api           # noqa: F401
    import ray_tpu.core.runtime_cluster  # noqa: F401
    import ray_tpu.cluster.worker_main as worker_main

    # Freeze the imported object graph into the permanent GC generation:
    # children never traverse it, so refcount/gc writes stop COW-faulting
    # the ~170MB of pre-imported module pages (the CPython zygote trick,
    # gc.freeze's documented purpose). Measurably cuts per-fork CPU on
    # single-core hosts and RSS growth everywhere.
    import gc
    gc.collect()
    gc.freeze()

    signal.signal(signal.SIGCHLD, signal.SIG_IGN)  # kernel reaps children
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(args.socket)
    except FileNotFoundError:
        pass
    srv.bind(args.socket)
    srv.listen(64)
    print("ZYGOTE_READY", flush=True)

    while True:
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        try:
            data = b""
            while not data.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                data += chunk
            if not data.strip():
                continue
            req = json.loads(data)
            pid = os.fork()
            if pid == 0:
                # -- child: become the worker ---------------------------
                try:
                    srv.close()
                    conn.close()
                    os.environ.update(req.get("env") or {})
                    if req.get("cwd"):
                        os.chdir(req["cwd"])
                    log_fd = os.open(req["log"],
                                     os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                                     0o644)
                    os.dup2(log_fd, 1)
                    os.dup2(log_fd, 2)
                    os.close(log_fd)
                    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                    sys.argv = ["worker_main"] + list(req["argv"])
                    worker_main.main()
                except BaseException:  # noqa: BLE001 - child must not
                    import traceback   # return into the accept loop
                    traceback.print_exc()
                finally:
                    # rtcheck: allow-exit(forked child: must not unwind into the zygote accept loop)
                    os._exit(0)
            conn.sendall(json.dumps({"pid": pid}).encode() + b"\n")
        except Exception:
            pass  # a malformed request must not kill the zygote
        finally:
            try:
                conn.close()
            except Exception:
                pass


if __name__ == "__main__":
    main()
