"""Durable control-plane state: snapshot + append-only journal.

Role parity: src/ray/gcs/gcs_server/gcs_table_storage.h (per-table durable
writes), store_client/redis_store_client.h (the backing store; here a local
file pair in the session dir — the conductor is single-node the way a
one-replica Redis is), and gcs_init_data.h (bulk load on restart).

Only DURABLE tables are journaled: nodes, actors, placement groups, KV,
function table, job counter. Volatile state (object directory, reference
counts, task events) is rebuilt after failover: node daemons re-advertise
their store contents when they observe a new conductor epoch, and ref
trackers resync their full ledger (core/refcount.py).

Format: both files are sequences of [4B little-endian length][pickle
(kind, data)] frames. ``<prefix>.snap`` holds one frame (a full snapshot);
``<prefix>.log`` holds mutations since that snapshot. Loads tolerate a torn
tail frame (crash mid-append) by stopping at the first bad frame.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Iterator, List, Optional, Tuple


def _read_frames(path: str) -> Iterator[Tuple[str, Any]]:
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                return
            (length,) = struct.unpack("<I", hdr)
            body = f.read(length)
            if len(body) < length:
                return  # torn tail: crash mid-append
            try:
                yield pickle.loads(body)
            except Exception:
                return


class StateJournal:
    """Append-mutations / snapshot-compaction pair for one conductor."""

    COMPACT_EVERY = 5000  # mutations between snapshots

    def __init__(self, prefix: str):
        self.snap_path = prefix + ".snap"
        self.log_path = prefix + ".log"
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._log_file = None
        self._appended = 0
        self._closed = False

    # -- load -----------------------------------------------------------
    def load(self) -> Tuple[Optional[dict], List[Tuple[str, Any]]]:
        """Returns (snapshot or None, ordered mutation records)."""
        snapshot = None
        for kind, data in _read_frames(self.snap_path):
            if kind == "snapshot":
                snapshot = data
        records = list(_read_frames(self.log_path))
        return snapshot, records

    # -- write ----------------------------------------------------------
    def _frame(self, kind: str, data: Any) -> bytes:
        body = pickle.dumps((kind, data), protocol=5)
        return struct.pack("<I", len(body)) + body

    def append(self, kind: str, data: Any) -> bool:
        """Append one mutation. Returns True when a compaction is due."""
        frame = self._frame(kind, data)
        with self._lock:
            if self._closed:
                return False
            if self._log_file is None:
                self._log_file = open(self.log_path, "ab")
            self._log_file.write(frame)
            self._log_file.flush()
            self._appended += 1
            return self._appended >= self.COMPACT_EVERY

    def snapshot(self, state: dict) -> None:
        """Write a full snapshot and truncate the journal."""
        tmp = self.snap_path + ".tmp"
        with self._lock:
            if self._closed:
                # a stopped conductor must never truncate files a same-dir
                # successor may already be journaling into
                return
            with open(tmp, "wb") as f:
                f.write(self._frame("snapshot", state))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            if self._log_file is not None:
                self._log_file.close()
            self._log_file = open(self.log_path, "wb")
            self._appended = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._log_file is not None:
                try:
                    self._log_file.close()
                except OSError:
                    pass
                self._log_file = None
