"""Durable control-plane state: snapshot + append-only journal.

Role parity: src/ray/gcs/gcs_server/gcs_table_storage.h (per-table durable
writes), store_client/redis_store_client.h (the backing store; here a local
file pair in the session dir — the conductor is single-node the way a
one-replica Redis is), and gcs_init_data.h (bulk load on restart).

Only DURABLE tables are journaled: nodes, actors, placement groups, KV,
function table, job counter. Volatile state (object directory, reference
counts, task events) is rebuilt after failover: node daemons re-advertise
their store contents when they observe a new conductor epoch, and ref
trackers resync their full ledger (core/refcount.py).

Format: files opening with the ``RTJ2`` magic hold [4B little-endian
length][4B CRC32][pickle(kind, data)] frames; files without it are the
legacy CRC-less [4B length][pickle] layout (still readable). The CRC
catches the failure the length prefix can't: a torn WRITE (power loss
mid-frame where the length landed but the body is short or garbage) that
still happens to parse — without it a half-written pickle can replay as a
wrong-but-valid mutation and silently poison recovery. ``load`` stops at
the first bad frame AND truncates the log back to the last good one, so
appends after restart never land beyond garbage the next reader would
stop at (orphaning everything after the tear).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from typing import Any, List, Optional, Tuple

_MAGIC = b"RTJ2"


def _scan(path: str) -> Tuple[List[Tuple[str, Any]], int]:
    """Parse every valid frame; returns (records, end offset of the last
    good frame — the truncation point for a torn/corrupt tail)."""
    records: List[Tuple[str, Any]] = []
    if not os.path.exists(path):
        return records, 0
    with open(path, "rb") as f:
        data = f.read()
    crc_mode = data[:4] == _MAGIC
    off = 4 if crc_mode else 0
    hdr = 8 if crc_mode else 4
    good = off
    while off + hdr <= len(data):
        if crc_mode:
            length, crc = struct.unpack_from("<II", data, off)
        else:
            (length,) = struct.unpack_from("<I", data, off)
            crc = None
        body = data[off + hdr:off + hdr + length]
        if len(body) < length:
            break  # torn tail: crash mid-append
        if crc is not None and zlib.crc32(body) != crc:
            break  # torn write: full-length but corrupt body
        try:
            records.append(pickle.loads(body))
        except Exception:
            break
        off += hdr + length
        good = off
    return records, good


def _file_crc_mode(path: str) -> bool:
    """Whether an existing journal file uses CRC framing (empty/missing
    files adopt it)."""
    try:
        with open(path, "rb") as f:
            head = f.read(4)
    except OSError:
        return True
    return len(head) < 4 or head == _MAGIC


class StateJournal:
    """Append-mutations / snapshot-compaction pair for one conductor."""

    COMPACT_EVERY = 5000  # mutations between snapshots

    def __init__(self, prefix: str):
        self.snap_path = prefix + ".snap"
        self.log_path = prefix + ".log"
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._log_file = None
        self._log_crc = True  # framing of the OPEN log file
        self._appended = 0
        self._closed = False

    # -- load -----------------------------------------------------------
    def load(self) -> Tuple[Optional[dict], List[Tuple[str, Any]]]:
        """Returns (snapshot or None, ordered mutation records). Truncates
        the log's torn tail so post-restore appends extend the good
        prefix instead of landing after garbage no reader reaches."""
        snapshot = None
        snap_records, _ = _scan(self.snap_path)
        for kind, data in snap_records:
            if kind == "snapshot":
                snapshot = data
        records, good_end = _scan(self.log_path)
        with self._lock:
            try:
                if os.path.exists(self.log_path) and \
                        os.path.getsize(self.log_path) > good_end and \
                        self._log_file is None and not self._closed:
                    with open(self.log_path, "r+b") as f:  # rtcheck: allow-blocking(journal lock serializes disk writes; no RPC under it)
                        f.truncate(good_end)
            except OSError:
                pass
        return snapshot, records

    # -- write ----------------------------------------------------------
    def _frame(self, kind: str, data: Any, crc_framed: bool = True) -> bytes:
        body = pickle.dumps((kind, data), protocol=5)
        if crc_framed:
            return struct.pack("<II", len(body), zlib.crc32(body)) + body
        return struct.pack("<I", len(body)) + body

    def append(self, kind: str, data: Any) -> bool:
        """Append one mutation. Returns True when a compaction is due."""
        with self._lock:
            if self._closed:
                return False
            if self._log_file is None:
                # Match the framing already on disk: mixing CRC frames
                # into a legacy-framed log would desync its reader.
                self._log_crc = _file_crc_mode(self.log_path)
                self._log_file = open(self.log_path, "ab")  # rtcheck: allow-blocking(journal lock serializes disk writes; no RPC under it)
                if self._log_crc and self._log_file.tell() == 0:
                    self._log_file.write(_MAGIC)
            self._log_file.write(self._frame(kind, data, self._log_crc))
            self._log_file.flush()
            self._appended += 1
            return self._appended >= self.COMPACT_EVERY

    def snapshot(self, state: dict) -> None:
        """Write a full snapshot and truncate the journal."""
        tmp = self.snap_path + ".tmp"
        with self._lock:
            if self._closed:
                # a stopped conductor must never truncate files a same-dir
                # successor may already be journaling into
                return
            with open(tmp, "wb") as f:  # rtcheck: allow-blocking(journal lock serializes disk writes; no RPC under it)
                f.write(_MAGIC)
                f.write(self._frame("snapshot", state))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            if self._log_file is not None:
                self._log_file.close()
            self._log_file = open(self.log_path, "wb")  # rtcheck: allow-blocking(journal lock serializes disk writes; no RPC under it)
            self._log_file.write(_MAGIC)
            self._log_crc = True
            self._appended = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._log_file is not None:
                try:
                    self._log_file.close()
                except OSError:
                    pass
                self._log_file = None
