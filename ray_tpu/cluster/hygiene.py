"""Stale-session sweeper: reclaim what crashed sessions left behind.

Role parity: the reference gets most of this for free from its process
model — plasma is one arena file whose pages die with the raylet
(plasma/store_runner.cc), and `ray stop` pkills the whole process family
(python/ray/scripts/scripts.py cleanup path). Our per-object shm segments
and Popen'd store/zygote daemons need an explicit reclaim path for the one
case no watchdog survives: SIGKILL of the whole tree.

Namespace swept (everything this framework creates is `rtpu-`-prefixed):
  /dev/shm/<prefix>*        — object segments; <prefix>owner names the
                              store pid (written by shmstored at startup)
  /tmp/rtpu-session-*       — session dirs; daemon.pid names the owner
  /tmp/ray_tpu/session-*    — CLI head session dirs (same pidfile)
  /tmp/rtpu-ckpt-*,
  /tmp/rtpu-algo-*          — checkpoint scratch; owner.pid or age-based

Safety: a group is reclaimed ONLY when its recorded owner pid is dead, or
when it has no owner record AND is old enough that no live session can
still be mid-creation (no pidfile yet). Live sessions are never touched.
"""

from __future__ import annotations

import os
import re
import shutil
import time
from typing import List

SHM_DIR = "/dev/shm"
# rtpu-<8 hex>- : one group per shmstored instance (node_daemon.py
# store_prefix). The owner marker is <prefix>owner.
_SHM_GROUP = re.compile(r"^(rtpu-[0-9a-f]{8}-)")
_TMP_PATTERNS = ("rtpu-session-",)
# Checkpoint/algo scratch may legitimately outlive its creating process
# (Checkpoint dirs are handed across workers on the same host, and a
# 30h experiment's checkpoints are live user data regardless of age) —
# swept only on EXPLICIT teardown (`stop`), only when very old.
_SCRATCH_PATTERNS = ("rtpu-ckpt-", "rtpu-algo-")
_SCRATCH_MAX_AGE_S = 24 * 3600.0
# Grace before reclaiming anything that carries no owner record: covers
# the window between mkdtemp/shm_open and the pidfile/marker write.
_NO_OWNER_GRACE_S = 120.0


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _read_pid(path: str) -> int:
    try:
        with open(path) as f:
            return int(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return -1


def write_pidfile(directory: str) -> None:
    """Record this process as the directory's owner (read by the sweep)."""
    try:
        tmp = os.path.join(directory, ".pid.tmp")
        with open(tmp, "w") as f:
            f.write(f"{os.getpid()}\n")
        os.replace(tmp, os.path.join(directory, "daemon.pid"))
    except OSError:
        pass


def sweep_shm(now: float | None = None) -> List[str]:
    """Unlink /dev/shm segment groups whose owning store is dead."""
    removed: List[str] = []
    now = now or time.time()
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return removed
    groups = {}
    for name in names:
        m = _SHM_GROUP.match(name)
        if m:
            groups.setdefault(m.group(1), []).append(name)
    for prefix, members in groups.items():
        owner = os.path.join(SHM_DIR, prefix + "owner")
        pid = _read_pid(owner)
        if pid > 0 and _pid_alive(pid):
            continue
        if pid <= 0:
            # No owner marker (pre-marker leak, or marker write raced):
            # only reclaim once the group is stale beyond doubt.
            try:
                age = now - max(os.path.getmtime(os.path.join(SHM_DIR, n))
                                for n in members)
            except OSError:
                age = _NO_OWNER_GRACE_S + 1
            if age < _NO_OWNER_GRACE_S:
                continue
        for n in members:
            try:
                os.unlink(os.path.join(SHM_DIR, n))
                removed.append(n)
            except OSError:
                pass
    return removed


def sweep_tmp(now: float | None = None,
              include_scratch: bool = False) -> List[str]:
    """Remove session dirs whose owner died; scratch only on request."""
    removed: List[str] = []
    now = now or time.time()
    roots = []
    for name in _TMP_PATTERNS:
        try:
            roots += [os.path.join("/tmp", d) for d in os.listdir("/tmp")
                      if d.startswith(name)]
        except OSError:
            pass
    for d in roots:
        if not os.path.isdir(d):
            continue
        pid = _read_pid(os.path.join(d, "daemon.pid"))
        if pid > 0 and _pid_alive(pid):
            continue
        if pid <= 0:
            try:
                if now - os.path.getmtime(d) < _NO_OWNER_GRACE_S:
                    continue
            except OSError:
                pass
        shutil.rmtree(d, ignore_errors=True)
        removed.append(d)
    for name in _SCRATCH_PATTERNS if include_scratch else ():
        try:
            scratch = [os.path.join("/tmp", x) for x in os.listdir("/tmp")
                       if x.startswith(name)]
        except OSError:
            scratch = []
        for d in scratch:
            try:
                if now - os.path.getmtime(d) < _SCRATCH_MAX_AGE_S:
                    continue
            except OSError:
                continue
            shutil.rmtree(d, ignore_errors=True)
            removed.append(d)
    # CLI head sessions (/tmp/ray_tpu/session-<port>) persist the conductor
    # journal ON PURPOSE — a head restarted on the same port recovers from
    # it (gcs_init_data.h role). Reclaim only the ephemeral state of dead
    # sessions: spill files and stale sockets, never conductor/.
    cli_root = "/tmp/ray_tpu"
    if os.path.isdir(cli_root):
        for name in os.listdir(cli_root):
            d = os.path.join(cli_root, name)
            if not (name.startswith("session-") and os.path.isdir(d)):
                continue
            pid = _read_pid(os.path.join(d, "daemon.pid"))
            if pid > 0 and _pid_alive(pid):
                continue
            spill = os.path.join(d, "spill")
            if os.path.isdir(spill):
                shutil.rmtree(spill, ignore_errors=True)
                removed.append(spill)
            for f in os.listdir(d):
                if f.endswith(".sock"):
                    try:
                        os.unlink(os.path.join(d, f))
                        removed.append(os.path.join(d, f))
                    except OSError:
                        pass
    return removed


def sweep_stale(include_scratch: bool = False) -> List[str]:
    """Full sweep; returns what was reclaimed. Cheap when nothing is stale
    (a listdir + a few kill(pid, 0) probes) — safe to run at every session
    start, `stop`, and bench pre-flight. `include_scratch` (explicit
    teardown only) additionally ages out old checkpoint scratch."""
    return sweep_shm() + sweep_tmp(include_scratch=include_scratch)
