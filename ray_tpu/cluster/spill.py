"""Coordinated spill backend: the object store's durable second tier.

Role parity: src/ray/raylet/local_object_manager.h — the raylet-side
component that writes cold primary copies out of plasma into external
storage and reports their URLs to the owner/GCS, so the object
directory can hand a spill URL to any restorer even after the writing
node is gone. The byte I/O here reuses the workflow/tune ``Storage``
backends, so one root string selects node-local directory (default),
shared directory, or URI scheme (mock://, fsspec gs:// / s3://) — a
shared root is what makes spill copies survive node death.

URL format: ``<root>/<oid.hex()>`` — self-describing. Any process (the
conductor deleting on ref-drop, a peer restoring after the writer
died) operates on a URL with no backend registry: split on the last
'/' and hand the root back to ``storage_for``.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ray_tpu.workflow.storage import storage_for


def _is_uri(root: str) -> bool:
    from ray_tpu.tune.syncer import is_uri
    return is_uri(root)


class SpillBackend:
    """Writes sealed object bytes under a root path/URI, keyed by oid
    hex. One instance per node daemon."""

    def __init__(self, root: str):
        self.root = root.rstrip("/")
        if not _is_uri(self.root):
            os.makedirs(self.root, exist_ok=True)
        self._storage = storage_for(self.root)

    def url_for(self, oid: bytes) -> str:
        return f"{self.root}/{oid.hex()}"

    def write(self, oid: bytes, data) -> str:
        """Write one object's bytes (bytes-like / memoryview). Returns
        the spill URL to report via rpc_add_spilled."""
        self._storage.put_bytes(oid.hex(), bytes(data))
        return self.url_for(oid)

    def read(self, oid: bytes) -> bytes:
        return self._storage.get_bytes(oid.hex())

    def exists(self, oid: bytes) -> bool:
        return self._storage.exists(oid.hex())

    def delete(self, oid: bytes) -> None:
        delete_url(self.url_for(oid))


def split_url(url: str) -> Tuple[str, str]:
    root, _, key = url.rpartition("/")
    return root, key


def read_url(url: str) -> bytes:
    """Restore an object's bytes from its spill URL (any process)."""
    root, key = split_url(url)
    return storage_for(root).get_bytes(key)


def local_path(url: str) -> Optional[str]:
    """Filesystem path behind a plain-directory spill URL (None for URI
    schemes). Lets the daemon that spilled an object serve fetch_chunk
    with a plain seek+read from the spill file — no shm re-inflation."""
    root, key = split_url(url)
    if _is_uri(root):
        return None
    return os.path.join(root, key)


def delete_url(url: str) -> None:
    """Delete one spill copy by URL (conductor ref-drop path). Missing
    files/keys are fine — deletes race benignly with the writing node's
    own cleanup."""
    root, key = split_url(url)
    if _is_uri(root):
        try:
            storage_for(root).delete_prefix(key)
        except Exception:
            pass
        return
    # FileStorage.delete_prefix rmtree's directories and ignores plain
    # files; spill entries ARE plain files, so unlink directly.
    try:
        os.unlink(os.path.join(root, key))
    except OSError:
        pass
