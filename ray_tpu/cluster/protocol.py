"""Control-plane RPC: length-prefixed pickle frames over TCP.

Role parity: src/ray/rpc/grpc_server.h / grpc_client.h — the reference wraps
gRPC; here the control plane is a small threaded RPC layer (the data plane
never goes through it: large objects move via the shm store and node-to-node
chunk streaming in node_daemon.py, and dense math moves over ICI via XLA
collectives).

Wire format: [4B little-endian length][payload] both ways. Two frame
shapes coexist on the request side:

- classic: ``(method, kwargs)`` — one in-flight request per connection,
  response ``(ok, payload)``. Clients pool one socket per concurrent caller.
- pipelined: ``(seq, method, kwargs)`` — many requests in flight per socket;
  the server dispatches each frame on a per-connection pool and replies
  ``(seq, ok, payload)`` in completion order, the client matches by seq
  (parity: gRPC HTTP/2 stream multiplexing, grpc_client.h).

``__batch__`` is a virtual method multiplexing N calls into one frame
(parity: the reference's batched GCS RPCs); it rides either frame shape.

Payload encoding: plain pickle (protocol 5, first byte 0x80), OR — when the
frame carries large binary data (object chunks: fetch_chunk replies,
push_chunk requests) — an out-of-band form (first byte 0x01) where every
``pickle.PickleBuffer`` ≥ _OOB_MIN_BYTES stays a separate segment:

    [0x01][u32 nbuf][u64 len]*nbuf [u32 pickle_len][pickle][buf 0][buf 1]...

The sender never copies those buffers into the pickle stream (they go
straight from the source mapping to ``sendmsg``), and the receiver hands
them out as zero-copy memoryviews over the received frame — the data-plane
analog of the reference shipping chunk payloads as raw gRPC bytes rather
than re-serializing them (object_manager.h chunk transfer).
"""

from __future__ import annotations

import itertools
import os
import pickle
import socket
import socketserver
import struct
import tempfile
import threading
import time
import weakref
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.cluster import fault_plane
from ray_tpu.util import events as _events

# Live pipelined channels, for the rt_rpc_inflight gauge and the slow-op
# watchdog's in-flight frame scan (both sampled by the event flusher —
# never on the request path).
_pipe_channels: "weakref.WeakSet" = weakref.WeakSet()

# rpc.frame ring events aggregate this many frames per event (a slow
# frame flushes the aggregate immediately) — per-frame emission at
# task-fast-path rates would dominate the flusher's fold/ship budget.
_FRAME_AGG = 16


def _rpc_inflight_probe() -> Dict[str, float]:
    n = 0
    for ch in list(_pipe_channels):
        n += len(ch._pending)
    return {"rt_rpc_inflight": float(n),
            "rt_rpc_channels": float(len(_pipe_channels))}


def _rpc_inflight_scan() -> List[tuple]:
    """(kind, ident, elapsed_s) for every in-flight pipelined frame — the
    watchdog's view of stuck RPCs, read from the channels' meta sidecars
    so the request path pays no watchdog registration."""
    out = []
    now = time.monotonic()
    for ch in list(_pipe_channels):
        with ch._lock:
            metas = list(ch._meta.values())
        out.extend(("rpc", m[2], now - m[0]) for m in metas)
    return out


_events.register_probe("rpc", _rpc_inflight_probe)
_events.register_inflight_scan("rpc", _rpc_inflight_scan)


def _uds_path(port: int) -> str:
    """Filesystem rendezvous for the same-host fast path: every server
    listening on 127.0.0.1:<port> ALSO listens on this Unix socket, and
    loopback clients prefer it (a UDS round trip skips the TCP/IP stack —
    measurably cheaper send syscalls on the task push ping-pong). The path
    is derived from the port alone so a client needs nothing beyond the
    ordinary host:port address to find it."""
    return os.path.join(tempfile.gettempdir(), f"rtpu-rpc-{port}.sock")


def _uds_enabled() -> bool:
    from ray_tpu import config
    try:
        return bool(config.get("rpc_same_host_uds"))
    except Exception:
        return True


_frame_cap_gen: Optional[int] = None
_frame_cap_v = 0


def _frame_cap() -> int:
    """rpc_message_max_bytes, cached on the config generation (read per
    received frame — too hot for a raw config.get)."""
    global _frame_cap_gen, _frame_cap_v
    from ray_tpu import config
    if _frame_cap_gen != config.generation:
        _frame_cap_v = int(config.get("rpc_message_max_bytes"))
        _frame_cap_gen = config.generation
    return _frame_cap_v


def _connect_timeout() -> float:
    from ray_tpu import config
    try:
        return float(config.get("rpc_connect_timeout_s"))
    except Exception:
        return 10.0


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class _PooledSocketDead(RpcError):
    """Internal: a cached keep-alive socket failed; retry on a fresh one."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


# Buffers at or above this size are shipped out-of-band (never copied into
# the pickle stream). Below it the copy is cheaper than the extra iovec.
_OOB_MIN_BYTES = 256 * 1024


def oob(data) -> Any:
    """Wrap a bytes-like for frame serialization: payloads at or above the
    out-of-band threshold ride as zero-copy iovec segments (the caller's
    buffer is sendmsg()'d directly); smaller ones pickle in-band, where
    the copy is cheaper than the extra segment. Used by bulk-payload call
    sites (compiled-graph channel_write frames) so they inherit whichever
    path is optimal without reimplementing the cutoff."""
    m = memoryview(data)
    if m.nbytes >= _OOB_MIN_BYTES:
        return pickle.PickleBuffer(m)
    return data if isinstance(data, bytes) else bytes(m)


def _dumps_parts(obj: Any) -> List[Any]:
    """Serialize to a list of buffer segments for scatter-send.

    Large ``pickle.PickleBuffer`` values inside ``obj`` stay zero-copy: the
    pickle stream only records a placeholder and the raw buffer rides the
    wire as its own segment (see the module docstring for the layout)."""
    bufs: List[memoryview] = []

    def _cb(pb: pickle.PickleBuffer) -> bool:
        # Truthy return = serialize in-band; falsy = keep out-of-band.
        try:
            view = pb.raw()
        except BufferError:
            return True  # non-contiguous: fall back in-band
        if view.nbytes < _OOB_MIN_BYTES:
            return True
        bufs.append(view)
        return False

    pkl = pickle.dumps(obj, protocol=5, buffer_callback=_cb)
    if not bufs:
        return [pkl]
    header = struct.pack("<BI", 1, len(bufs)) \
        + b"".join(struct.pack("<Q", v.nbytes) for v in bufs) \
        + struct.pack("<I", len(pkl))
    return [header, pkl, *bufs]


def _loads_frame(payload: Any) -> Any:
    """Inverse of _dumps_parts over one received frame payload.

    Out-of-band buffers come back as memoryviews over the receive buffer —
    no per-chunk copy between socket and consumer."""
    if not payload or payload[0] != 1:
        return pickle.loads(payload)
    mv = memoryview(payload)
    (nbuf,) = struct.unpack_from("<I", mv, 1)
    off = 5
    lens = struct.unpack_from("<%dQ" % nbuf, mv, off)
    off += 8 * nbuf
    (pklen,) = struct.unpack_from("<I", mv, off)
    off += 4
    pkl = mv[off:off + pklen]
    off += pklen
    bufs = []
    for n in lens:
        bufs.append(mv[off:off + n])
        off += n
    return pickle.loads(pkl, buffers=bufs)


def _send_parts(sock: socket.socket, parts: List[Any]) -> None:
    """Scatter-send [length][part0][part1]... without concatenating: one
    sendmsg per iovec batch straight from the source buffers (for chunk
    transfers that means directly out of the pinned shm mapping)."""
    if len(parts) == 1:
        # Plain frame (no out-of-band buffers) — the common control-plane
        # case: one small concat + sendall beats iovec bookkeeping.
        payload = parts[0]
        sock.sendall(struct.pack("<I", len(payload)) + payload)
        return
    views = [memoryview(p).cast("B") for p in parts]
    total = sum(v.nbytes for v in views)
    views.insert(0, memoryview(struct.pack("<I", total)))
    while views:
        sent = sock.sendmsg(views)
        while views and sent >= views[0].nbytes:
            sent -= views[0].nbytes
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionLost("connection closed")
        got += r
    return buf


def _recv_frame(sock: socket.socket) -> bytearray:
    (length,) = struct.unpack("<I", _recv_exact(sock, 4))
    if length > _frame_cap():
        # A corrupt/malicious length prefix must not allocate gigabytes;
        # the connection is unrecoverable (stream offset is lost).
        raise ConnectionLost(
            f"frame length {length} exceeds rpc_message_max_bytes "
            f"({_frame_cap()})")
    return _recv_exact(sock, length)


def _dispatch(service: Any, method: str, kwargs: dict) -> Tuple[bool, Any]:
    """Resolve and run one method; exceptions become the payload."""
    try:
        # Fault point: delay/raise before serving (subsumes the old
        # _maybe_inject_delay / testing_rpc_delay_us hook). A raise here
        # ships to the caller as the call's error payload — a handler
        # failure, not a transport failure.
        fault_plane.fire("rpc.server.dispatch", method=method)
        if method == "__batch__":
            return True, [_dispatch(service, m, kw)
                          for m, kw in kwargs["calls"]]
        fn = getattr(service, "rpc_" + method, None)
        if fn is None:
            return False, RpcError(f"no such method: {method}")
        return True, fn(**kwargs)
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 - shipped to caller
        return False, e


def _safe_dumps(resp: tuple) -> List[Any]:
    try:
        return _dumps_parts(resp)
    except Exception:
        # Replace the unpicklable payload, keep the frame shape (a seq
        # prefix must survive so pipelined callers still match it).
        err = RpcError("unpicklable response")
        fallback = resp[:-2] + (False, err)
        return [pickle.dumps(fallback, protocol=5)]


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server._conns.add(self.request)  # type: ignore[attr-defined]
        self._pool: Optional[ThreadPoolExecutor] = None
        self._send_lock = threading.Lock()

    def finish(self):
        self.server._conns.discard(self.request)  # type: ignore[attr-defined]
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def _respond(self, resp: tuple) -> None:
        parts = _safe_dumps(resp)
        with self._send_lock:
            _send_parts(self.request, parts)

    def _sever(self) -> None:
        try:
            self.request.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.request.close()
        except OSError:
            pass

    def _run_pipelined(self, service: Any, seq: int, method: str,
                       kwargs: dict) -> None:
        ok, payload = _dispatch(service, method, kwargs)
        # Fault point: lose the reply after the handler ran — the
        # "committed but unacked" window every idempotent/deduped op must
        # survive. drop_reply loses just this frame; sever kills the whole
        # connection (and with it every pipelined call in flight).
        act = fault_plane.fire("rpc.server.reply", method=method)
        if act == "drop_reply":
            return
        if act == "sever":
            self._sever()
            return
        try:
            self._respond((seq, ok, payload))
        except OSError:
            pass  # peer gone; the read loop notices and exits

    def handle(self):
        sock = self.request
        if sock.family != socket.AF_UNIX:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        service = self.server.service  # type: ignore[attr-defined]
        while True:
            try:
                req = _recv_frame(sock)
            except (ConnectionLost, OSError):
                return
            try:
                frame = _loads_frame(req)
                if len(frame) == 3:
                    seq, method, kwargs = frame
                else:
                    seq, (method, kwargs) = None, frame
            except Exception:
                return
            if seq is not None:
                # Pipelined frame: normally dispatched off-thread so the
                # read loop keeps draining — a long-poll must not
                # head-of-line-block the requests queued behind it on this
                # socket. Services whose pipelined callers are strictly
                # request-at-a-time per channel (the worker: one in-flight
                # push per lease / per-actor ordered pushers) opt into
                # INLINE dispatch via ``rpc_inline_pipelined`` and skip
                # the executor handoff — a thread wake per push on the
                # task round-trip critical path.
                if getattr(service, "rpc_inline_pipelined", False):
                    self._run_pipelined(service, seq, method, kwargs)
                    continue
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="rpc-pipe")
                self._pool.submit(self._run_pipelined, service, seq,
                                  method, kwargs)
                continue
            # Classic frame: dispatch inline (no thread handoff on the
            # latency-critical single-call path).
            resp = _dispatch(service, method, kwargs)
            act = fault_plane.fire("rpc.server.reply", method=method)
            if act == "drop_reply":
                continue
            if act == "sever":
                self._sever()
                return
            try:
                self._respond(resp)
            except OSError:
                return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        super().__init__(*args, **kwargs)


class _UnixServer(socketserver.ThreadingUnixStreamServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        super().__init__(*args, **kwargs)


class RpcServer:
    """Serves ``rpc_*`` methods of a service object on host:port.

    Handlers run on a thread per connection; blocking inside a handler (e.g.
    a long-poll wait on a condition variable) only stalls that client.

    Alongside the TCP listener, the server binds a Unix socket at
    ``_uds_path(port)`` (same handler, same service): loopback clients
    connect there instead of through the TCP/IP stack. Failover-safe by
    the same port-takeover convention as TCP — a successor binding the
    port unlinks and re-binds the path.
    """

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0):
        self._srv = _Server((host, port), _Handler)
        self._srv.service = service  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self.address = f"{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name=f"rpc-{type(service).__name__}")
        self._thread.start()
        self._usrv: Optional[_UnixServer] = None
        self._upath: Optional[str] = None
        if _uds_enabled():
            try:
                path = _uds_path(self.port)
                try:
                    os.unlink(path)   # stale socket from a dead predecessor
                except FileNotFoundError:
                    pass
                self._usrv = _UnixServer(path, _Handler)
                self._usrv.service = service  # type: ignore[attr-defined]
                self._upath = path
                threading.Thread(
                    target=self._usrv.serve_forever, daemon=True,
                    name=f"rpc-uds-{type(service).__name__}").start()
            except OSError:
                self._usrv = None   # TCP alone still serves everything

    def stop(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass
        if self._usrv is not None:
            try:
                self._usrv.shutdown()
                self._usrv.server_close()
            except OSError:
                pass
            try:
                if self._upath:
                    os.unlink(self._upath)
            except OSError:
                pass
        # Sever live connections too: a handler thread parked on recv would
        # otherwise keep serving this (dead) service's stale in-memory
        # state to clients holding pooled sockets — fatal for failover,
        # where a successor binds the same port.
        conns = list(self._srv._conns)
        if self._usrv is not None:
            conns += list(self._usrv._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class _PipeChannel:
    """One pipelined connection: sequence-numbered frames, a reader thread
    matching responses to waiting futures. Many callers share one socket
    (the classic pool opens one socket per concurrent caller instead)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        # Flight-recorder sidecar: seq -> (t_send, bytes, method). Only
        # populated while events are enabled; popped with the matching
        # future so it can never grow past _pending. The slow-op watchdog
        # reads it via _rpc_inflight_scan, so frames need no per-call
        # watchdog registration.
        self._meta: Dict[int, tuple] = {}
        # Reader-thread-only rpc.frame aggregation [frames, bytes]: one
        # ring event per _FRAME_AGG frames (or any slow frame) keeps the
        # per-frame hot-path cost to two dict ops.
        self._agg = [0, 0]
        self._transport = ("uds" if sock.family == socket.AF_UNIX
                           else "tcp")
        self._seq = itertools.count()
        self.dead: Optional[BaseException] = None
        _pipe_channels.add(self)
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="rpc-pipe-reader")
        self._reader.start()

    def request(self, method: str, kwargs: dict) -> Future:
        fut: Future = Future()
        seq = next(self._seq)
        parts = _dumps_parts((seq, method, kwargs))
        record = _events.enabled()
        nbytes = sum(memoryview(p).nbytes for p in parts) if record else 0
        with self._lock:
            if self.dead is not None:
                fut.set_exception(ConnectionLost(str(self.dead)))
                return fut
            self._pending[seq] = fut
            if record:
                # Before the send: the reply (and the reader popping the
                # meta) can only race a meta recorded after it.
                self._meta[seq] = (time.monotonic(), nbytes, method)
        try:
            # Fault point: client-side loss on the pipelined channel. sever
            # closes the shared socket, so the send below (or the reader
            # thread) fails and _fail_all promptly fails EVERY pending
            # future — the fail-fast contract chaos tests pin down.
            if fault_plane.fire("rpc.client.send", method=method,
                                pipelined=True) == "sever":
                self._sock.close()
            with self._send_lock:
                _send_parts(self._sock, parts)
        except BaseException as e:  # noqa: BLE001
            with self._lock:
                self._pending.pop(seq, None)
                self._meta.pop(seq, None)
            self._fail_all(e)
            if not fut.done():
                fut.set_exception(ConnectionLost(repr(e)))
        return fut

    def _read_loop(self) -> None:
        while True:
            try:
                seq, ok, payload = _loads_frame(_recv_frame(self._sock))
            except BaseException as e:  # noqa: BLE001 - socket died
                self._fail_all(e)
                return
            with self._lock:
                fut = self._pending.pop(seq, None)
                meta = self._meta.pop(seq, None)
            if meta is not None:
                # Aggregated frame accounting (reader-thread-only state):
                # a ring event per _FRAME_AGG frames — or immediately for
                # a slow frame — carries the batch's frame/byte totals and
                # the triggering frame's latency as the sample.
                agg = self._agg
                agg[0] += 1
                agg[1] += meta[1]
                lat = time.monotonic() - meta[0]
                if agg[0] >= _FRAME_AGG or lat >= 0.01:
                    _events.emit("rpc.frame", meta[2], value=lat,
                                 attrs={"frames": agg[0], "bytes": agg[1],
                                        "transport": self._transport})
                    agg[0] = agg[1] = 0
            if fut is None:
                continue
            if ok:
                fut.set_result(payload)
            else:
                exc = payload if isinstance(payload, BaseException) \
                    else RpcError(str(payload))
                fut.set_exception(exc)

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            if self.dead is None:
                self.dead = exc
            pending, self._pending = self._pending, {}
            self._meta = {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(repr(exc)))
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        self._fail_all(ConnectionLost("channel closed"))


class RpcClient:
    """Pooled client: one socket per concurrent caller to one address.

    ``reconnect_s`` > 0 makes calls retry connection-level failures for up
    to that many seconds — the failover transparency window (a restarted
    conductor comes back on the same port; parity: the reference's GCS RPC
    client reconnection, gcs_rpc_client.h).

    Delivery contract: AT-LEAST-ONCE for every client. Independent of
    reconnect_s, a call whose POOLED keep-alive socket turns out dead is
    re-sent once on a fresh connection (ports get reused; a cached socket
    can point at a long-gone server). Services are designed for this:
    control-plane mutations are idempotent or dedupe by id (ref_update
    batch ids, actor push seqnos, task ids, lease ids).
    """

    def __init__(self, address: str, timeout: Optional[float] = None,
                 reconnect_s: float = 0.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._target = (host, int(port))
        self._timeout = timeout
        self._reconnect_s = reconnect_s
        self._free: list = []
        self._lock = threading.Lock()
        self._closed = False
        self._pipe: Optional[_PipeChannel] = None
        self._pipe_lock = threading.Lock()

    def _connect(self) -> socket.socket:
        host = self._target[0]
        if host in ("127.0.0.1", "localhost") and _uds_enabled():
            # Same-host fast path: the server mirrors its TCP listener on a
            # Unix socket. Any failure (no file, refused, server predates
            # the feature) falls straight back to TCP.
            path = _uds_path(self._target[1])
            if os.path.exists(path):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    s.settimeout(self._timeout if self._timeout is not None
                                 else _connect_timeout())
                    s.connect(path)
                    s.settimeout(self._timeout)
                    return s
                except OSError:
                    try:
                        s.close()
                    except OSError:
                        pass
        # Connection establishment is bounded by rpc_connect_timeout_s even
        # when per-call timeouts are unbounded (a dead peer must not hang
        # the caller in connect()); established-socket ops keep the
        # caller's timeout semantics.
        sock = socket.create_connection(
            self._target,
            timeout=self._timeout if self._timeout is not None
            else _connect_timeout())
        sock.settimeout(self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, _timeout: Optional[float] = None, **kwargs) -> Any:
        deadline = (time.monotonic() + self._reconnect_s
                    if self._reconnect_s > 0 else None)
        fresh_retry_done = False
        force_fresh = False
        while True:
            try:
                return self._call_once(method, _timeout, kwargs,
                                       force_fresh=force_fresh)
            except _PooledSocketDead as e:
                # A POOLED socket died under us. Ports get reused: the
                # process-wide client cache (get_client) can hold sockets
                # to a long-gone server whose host:port a NEW server now
                # owns (observed as cross-test flakes; same hazard as a
                # same-port conductor failover). Its pool-mates are stale
                # too — drop them all and retry once on a FRESH
                # connection; further failures follow the normal
                # reconnect-deadline policy.
                with self._lock:
                    stale, self._free = self._free, []
                for s in stale:
                    try:
                        s.close()
                    except OSError:
                        pass
                if not fresh_retry_done:
                    # Retry on a GUARANTEED fresh connection: a concurrent
                    # thread may repool another stale socket between our
                    # drain and the retry's pool pop.
                    fresh_retry_done = True
                    force_fresh = True
                    continue
                if deadline is None or time.monotonic() >= deadline or \
                        self._closed:
                    raise ConnectionLost("connection closed") from e
                time.sleep(0.1)
            except (ConnectionLost, ConnectionRefusedError,
                    ConnectionResetError, BrokenPipeError, OSError):
                if deadline is None or time.monotonic() >= deadline or \
                        self._closed:
                    raise
                time.sleep(0.1)

    def _call_once(self, method: str, _timeout: Optional[float],
                   kwargs: dict, force_fresh: bool = False) -> Any:
        sock = None
        if not force_fresh:
            with self._lock:
                sock = self._free.pop() if self._free else None
        pooled = sock is not None
        if sock is None:
            sock = self._connect()
        try:
            if _timeout is not None:
                sock.settimeout(_timeout)
            if fault_plane.fire("rpc.client.send", method=method) == "sever":
                sock.close()
            _send_parts(sock, _dumps_parts((method, kwargs)))
            if fault_plane.fire("rpc.client.recv", method=method) == "sever":
                sock.close()  # request sent, reply lost: the unacked window
            ok, payload = _loads_frame(_recv_frame(sock))
            if _timeout is not None:
                sock.settimeout(self._timeout)
        except BaseException as e:  # noqa: BLE001 - socket is poisoned either way; classified and re-raised below
            try:
                sock.close()
            except OSError:
                pass
            if pooled and isinstance(e, (ConnectionLost, ConnectionError,
                                         BrokenPipeError)):
                raise _PooledSocketDead() from e
            raise
        with self._lock:
            if self._closed:
                sock.close()
            else:
                self._free.append(sock)
        if not ok:
            raise payload if isinstance(payload, BaseException) else RpcError(
                str(payload))
        return payload

    # -- pipelined path ------------------------------------------------
    def _channel(self) -> _PipeChannel:
        with self._pipe_lock:
            if self._closed:
                raise ConnectionLost("client closed")
            if self._pipe is None or self._pipe.dead is not None:
                self._pipe = _PipeChannel(self._connect())
            return self._pipe

    def sever_pipe(self) -> None:
        """Kill the pipelined channel's socket mid-flight (the honor hook
        for data-plane "sever" fault actions: object.pull.window,
        object.push.chunk). Every pending call_async future on the channel
        fails fast with ConnectionLost via _fail_all."""
        with self._pipe_lock:
            pipe = self._pipe
        if pipe is not None:
            try:
                pipe._sock.close()
            except OSError:
                pass

    def call_async(self, method: str, _retry: bool = False,
                   **kwargs) -> Future:
        """Pipelined single-attempt call: returns a Future without waiting
        for the round-trip, so N calls overlap on one socket. No automatic
        resend by default — a severed channel fails the future FAST with
        ConnectionLost (never hangs; _PipeChannel._fail_all drains every
        pending future the moment the socket dies).

        ``_retry=True`` opts into async reconnect-and-retry under the same
        at-least-once contract as ``call``: on ConnectionLost the call is
        re-sent on a fresh channel (once immediately, then on a 100ms
        cadence until the reconnect_s window closes). ONLY safe for
        idempotent ops — conductor mutations dedupe by id, so its control
        ops qualify; an arbitrary service method may not."""
        if not _retry:
            return self._channel().request(method, kwargs)
        out: Future = Future()
        deadline = (time.monotonic() + self._reconnect_s
                    if self._reconnect_s > 0 else None)
        state = {"fresh_retry_done": False}

        def _issue() -> None:
            try:
                self._channel().request(method, kwargs) \
                    .add_done_callback(_on_done)
            except BaseException as e:  # noqa: BLE001 - connect failed
                _on_failure(e)

        def _on_done(fut: Future) -> None:
            exc = fut.exception()
            if exc is None:
                out.set_result(fut.result())
            elif isinstance(exc, ConnectionLost):
                _on_failure(exc)
            else:
                out.set_exception(exc)

        def _on_failure(exc: BaseException) -> None:
            if self._closed or (deadline is not None
                                and time.monotonic() >= deadline and
                                state["fresh_retry_done"]):
                out.set_exception(exc if isinstance(exc, ConnectionLost)
                                  else ConnectionLost(repr(exc)))
                return
            if not state["fresh_retry_done"]:
                # Stale cached channel: one immediate fresh-socket retry
                # (mirrors call/call_pipelined).
                state["fresh_retry_done"] = True
                _issue()
                return
            if deadline is None:
                out.set_exception(exc if isinstance(exc, ConnectionLost)
                                  else ConnectionLost(repr(exc)))
                return
            # Delayed retry off-thread: _on_failure runs on the reader
            # thread inside _fail_all — sleeping here would stall failing
            # the channel's other pending futures.
            t = threading.Timer(0.1, _issue)
            t.daemon = True
            t.start()

        _issue()
        return out

    def call_pipelined(self, method: str, _timeout: Optional[float] = None,
                       **kwargs) -> Any:
        """Sync call over the shared pipelined channel, with the same
        reconnect/at-least-once contract as ``call``."""
        deadline = (time.monotonic() + self._reconnect_s
                    if self._reconnect_s > 0 else None)
        fresh_retry_done = False
        while True:
            try:
                return self._channel().request(method, kwargs).result(
                    timeout=_timeout if _timeout is not None
                    else self._timeout)
            except ConnectionLost:
                if not fresh_retry_done:
                    fresh_retry_done = True  # stale cached channel: one
                    continue                 # immediate fresh-socket retry
                if deadline is None or time.monotonic() >= deadline or \
                        self._closed:
                    raise
                time.sleep(0.1)

    def call_batch(self, calls: List[Tuple[str, dict]],
                   _timeout: Optional[float] = None,
                   return_exceptions: bool = False) -> List[Any]:
        """Multiplex N method calls into ONE request frame (one round-trip,
        one lock-step on each side). Returns results in call order; a
        failed sub-call raises unless ``return_exceptions``."""
        outcomes = self.call("__batch__", _timeout=_timeout,
                             calls=[(m, kw) for m, kw in calls])
        results = []
        for ok, payload in outcomes:
            if ok:
                results.append(payload)
            elif return_exceptions:
                results.append(payload if isinstance(payload, BaseException)
                               else RpcError(str(payload)))
            else:
                raise payload if isinstance(payload, BaseException) \
                    else RpcError(str(payload))
        return results

    def close(self) -> None:
        with self._lock:
            self._closed = True
            socks, self._free = self._free, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        with self._pipe_lock:
            pipe, self._pipe = self._pipe, None
        if pipe is not None:
            pipe.close()


_client_pool: Dict[Tuple[str, Optional[float], float], RpcClient] = {}
_client_pool_lock = threading.Lock()


def get_client(address: str, timeout: Optional[float] = None,
               reconnect_s: float = 0.0) -> RpcClient:
    """Process-wide client cache (parity: rpc/worker/core_worker_client_pool.h)."""
    key = (address, timeout, reconnect_s)
    with _client_pool_lock:
        cli = _client_pool.get(key)
        if cli is None:
            cli = RpcClient(address, timeout=timeout,
                            reconnect_s=reconnect_s)
            _client_pool[key] = cli
        return cli


def drop_client(address: str) -> None:
    with _client_pool_lock:
        for key in [k for k in _client_pool if k[0] == address]:
            _client_pool.pop(key).close()
