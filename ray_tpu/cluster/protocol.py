"""Control-plane RPC: length-prefixed pickle frames over TCP.

Role parity: src/ray/rpc/grpc_server.h / grpc_client.h — the reference wraps
gRPC; here the control plane is a small threaded RPC layer (the data plane
never goes through it: large objects move via the shm store and node-to-node
chunk streaming in node_daemon.py, and dense math moves over ICI via XLA
collectives).

Wire format: [4B little-endian length][pickle((method, kwargs))] request,
[4B length][pickle((ok, payload))] response. One in-flight request per
connection; clients pool connections per target address.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class RpcError(Exception):
    pass


_delay_cache: tuple = (-1, None)  # (config generation, cached spec)


def _maybe_inject_delay(method: str) -> None:
    """Deterministic chaos-testing delay (parity: the reference's
    RAY_testing_asio_delay_us flag, ray_config_def.h:762, used by
    test_chaos.py to stretch 2PC windows). Set config
    ``testing_rpc_delay_us`` to "<us>" for all methods or
    "<method>:<us>[,<method>:<us>...]" to target specific RPCs."""
    global _delay_cache
    import time as _time

    from ray_tpu import config as _config
    gen, spec = _delay_cache
    if gen != _config.generation:
        # This runs on EVERY rpc; re-resolving through os.environ each
        # time measurably drags task throughput. set_system_config bumps
        # the generation, so chaos tests still flip it mid-run.
        spec = _config.get("testing_rpc_delay_us")
        _delay_cache = (_config.generation, spec)
    if not spec:
        return
    spec = str(spec)
    if ":" in spec:
        for part in spec.split(","):
            name, _, us = part.partition(":")
            if name == method and us.isdigit():
                _time.sleep(int(us) / 1e6)
                return
    elif spec.isdigit() and int(spec):
        _time.sleep(int(spec) / 1e6)


class ConnectionLost(RpcError):
    pass


class _PooledSocketDead(RpcError):
    """Internal: a cached keep-alive socket failed; retry on a fresh one."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionLost("connection closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, length)


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server._conns.add(self.request)  # type: ignore[attr-defined]

    def finish(self):
        self.server._conns.discard(self.request)  # type: ignore[attr-defined]

    def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        service = self.server.service  # type: ignore[attr-defined]
        while True:
            try:
                req = _recv_frame(sock)
            except (ConnectionLost, OSError):
                return
            try:
                method, kwargs = pickle.loads(req)
                _maybe_inject_delay(method)
                fn = getattr(service, "rpc_" + method, None)
                if fn is None:
                    resp = (False, RpcError(f"no such method: {method}"))
                else:
                    resp = (True, fn(**kwargs))
            except SystemExit:
                raise
            except BaseException as e:  # noqa: BLE001 - shipped to caller
                try:
                    resp = (False, e)
                except Exception:
                    resp = (False, RpcError(repr(e)))
            try:
                _send_frame(sock, pickle.dumps(resp, protocol=5))
            except (OSError, pickle.PicklingError):
                try:
                    _send_frame(sock, pickle.dumps(
                        (False, RpcError("unpicklable response")), protocol=5))
                except OSError:
                    return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        super().__init__(*args, **kwargs)


class RpcServer:
    """Serves ``rpc_*`` methods of a service object on host:port.

    Handlers run on a thread per connection; blocking inside a handler (e.g.
    a long-poll wait on a condition variable) only stalls that client.
    """

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0):
        self._srv = _Server((host, port), _Handler)
        self._srv.service = service  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self.address = f"{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name=f"rpc-{type(service).__name__}")
        self._thread.start()

    def stop(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass
        # Sever live connections too: a handler thread parked on recv would
        # otherwise keep serving this (dead) service's stale in-memory
        # state to clients holding pooled sockets — fatal for failover,
        # where a successor binds the same port.
        for sock in list(self._srv._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class RpcClient:
    """Pooled client: one socket per concurrent caller to one address.

    ``reconnect_s`` > 0 makes calls retry connection-level failures for up
    to that many seconds — the failover transparency window (a restarted
    conductor comes back on the same port; parity: the reference's GCS RPC
    client reconnection, gcs_rpc_client.h).

    Delivery contract: AT-LEAST-ONCE for every client. Independent of
    reconnect_s, a call whose POOLED keep-alive socket turns out dead is
    re-sent once on a fresh connection (ports get reused; a cached socket
    can point at a long-gone server). Services are designed for this:
    control-plane mutations are idempotent or dedupe by id (ref_update
    batch ids, actor push seqnos, task ids, lease ids).
    """

    def __init__(self, address: str, timeout: Optional[float] = None,
                 reconnect_s: float = 0.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._target = (host, int(port))
        self._timeout = timeout
        self._reconnect_s = reconnect_s
        self._free: list = []
        self._lock = threading.Lock()
        self._closed = False

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._target, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, _timeout: Optional[float] = None, **kwargs) -> Any:
        deadline = (time.monotonic() + self._reconnect_s
                    if self._reconnect_s > 0 else None)
        fresh_retry_done = False
        force_fresh = False
        while True:
            try:
                return self._call_once(method, _timeout, kwargs,
                                       force_fresh=force_fresh)
            except _PooledSocketDead as e:
                # A POOLED socket died under us. Ports get reused: the
                # process-wide client cache (get_client) can hold sockets
                # to a long-gone server whose host:port a NEW server now
                # owns (observed as cross-test flakes; same hazard as a
                # same-port conductor failover). Its pool-mates are stale
                # too — drop them all and retry once on a FRESH
                # connection; further failures follow the normal
                # reconnect-deadline policy.
                with self._lock:
                    stale, self._free = self._free, []
                for s in stale:
                    try:
                        s.close()
                    except OSError:
                        pass
                if not fresh_retry_done:
                    # Retry on a GUARANTEED fresh connection: a concurrent
                    # thread may repool another stale socket between our
                    # drain and the retry's pool pop.
                    fresh_retry_done = True
                    force_fresh = True
                    continue
                if deadline is None or time.monotonic() >= deadline or \
                        self._closed:
                    raise ConnectionLost("connection closed") from e
                time.sleep(0.1)
            except (ConnectionLost, ConnectionRefusedError,
                    ConnectionResetError, BrokenPipeError, OSError):
                if deadline is None or time.monotonic() >= deadline or \
                        self._closed:
                    raise
                time.sleep(0.1)

    def _call_once(self, method: str, _timeout: Optional[float],
                   kwargs: dict, force_fresh: bool = False) -> Any:
        sock = None
        if not force_fresh:
            with self._lock:
                sock = self._free.pop() if self._free else None
        pooled = sock is not None
        if sock is None:
            sock = self._connect()
        try:
            if _timeout is not None:
                sock.settimeout(_timeout)
            _send_frame(sock, pickle.dumps((method, kwargs), protocol=5))
            ok, payload = pickle.loads(_recv_frame(sock))
            if _timeout is not None:
                sock.settimeout(self._timeout)
        except BaseException as e:
            try:
                sock.close()
            except OSError:
                pass
            if pooled and isinstance(e, (ConnectionLost, ConnectionError,
                                         BrokenPipeError)):
                raise _PooledSocketDead() from e
            raise
        with self._lock:
            if self._closed:
                sock.close()
            else:
                self._free.append(sock)
        if not ok:
            raise payload if isinstance(payload, BaseException) else RpcError(
                str(payload))
        return payload

    def close(self) -> None:
        with self._lock:
            self._closed = True
            socks, self._free = self._free, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


_client_pool: Dict[Tuple[str, Optional[float], float], RpcClient] = {}
_client_pool_lock = threading.Lock()


def get_client(address: str, timeout: Optional[float] = None,
               reconnect_s: float = 0.0) -> RpcClient:
    """Process-wide client cache (parity: rpc/worker/core_worker_client_pool.h)."""
    key = (address, timeout, reconnect_s)
    with _client_pool_lock:
        cli = _client_pool.get(key)
        if cli is None:
            cli = RpcClient(address, timeout=timeout,
                            reconnect_s=reconnect_s)
            _client_pool[key] = cli
        return cli


def drop_client(address: str) -> None:
    with _client_pool_lock:
        for key in [k for k in _client_pool if k[0] == address]:
            _client_pool.pop(key).close()
