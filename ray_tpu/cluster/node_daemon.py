"""Node daemon: per-node runtime (raylet equivalent).

Role parity: src/ray/raylet/node_manager.h:115 — grants worker leases
(node_manager.cc:1847 HandleRequestWorkerLease) with queueing and spillback,
runs the worker pool (worker_pool.h:156: spawn, startup-token handshake,
idle cache), reserves placement-group bundles via 2PC prepare/commit
(placement_group_resource_manager.h), serves node-to-node object transfer
in chunks (object_manager.h:117 push/pull path), and reports worker/actor
death to the conductor.

One daemon per node. It owns the node's shm object store (shmstored) the
way the raylet colocates plasma (plasma/store_runner.cc).
"""

from __future__ import annotations

import os
import pickle
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import config
from ray_tpu.cluster import fault_plane, object_client
from ray_tpu.cluster.protocol import RpcServer, get_client
from ray_tpu.util import events as _events
from ray_tpu.util import lockcheck

CHUNK_SIZE = 8 << 20  # object transfer chunk (reference uses 5MiB chunks)


class _DaemonStopping(RuntimeError):
    """Raised by spawn paths once stop() begins tearing the session down;
    callers treat it as 'no worker available', never as a crash."""


class _ForkedProc:
    """Popen-compatible handle over a zygote-forked worker. The child's
    PARENT is the zygote (which SIG_IGNs SIGCHLD so the kernel reaps —
    no zombie pins the pid), so liveness is tracked through a pidfd: the
    fd references THIS process, so a recycled pid can never masquerade as
    the live worker. Falls back to signal-0 probing where pidfd is
    unavailable."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._pidfd = -1
        try:
            self._pidfd = os.pidfd_open(pid)
        except ProcessLookupError:
            # Already exited and kernel-reaped (the zygote SIG_IGNs
            # SIGCHLD, so the pid frees immediately). Falling back to
            # kill(pid, 0) here would let a RECYCLED pid make this dead
            # worker look alive indefinitely — record death now.
            self.returncode = 1
        except Exception:
            # pidfd unsupported (ENOSYS etc): signal-0 probing is the only
            # liveness signal available.
            pass

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            if self._pidfd >= 0:
                signal.pidfd_send_signal(self._pidfd, 0)
            else:
                os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            # Exit status unobservable (the kernel reaped the child);
            # report generic nonzero.
            self.returncode = 1
            if self._pidfd >= 0:
                os.close(self._pidfd)
                self._pidfd = -1
            return 1
        except PermissionError:
            return None

    def kill(self) -> None:
        try:
            if self._pidfd >= 0:
                signal.pidfd_send_signal(self._pidfd, signal.SIGKILL)
            else:
                os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    # Popen-interface stubs used by supervisors.
    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return 1

    def terminate(self) -> None:
        try:
            if self._pidfd >= 0:
                signal.pidfd_send_signal(self._pidfd, signal.SIGTERM)
            else:
                os.kill(self.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def __del__(self):
        if self._pidfd >= 0:
            try:
                os.close(self._pidfd)
            except Exception:
                # OSError, or AttributeError/TypeError during interpreter
                # shutdown (the os module may already be torn down).
                pass
            self._pidfd = -1


class _Worker:
    def __init__(self, proc, token: str, env_key: str):
        self.proc = proc
        self.token = token
        self.env_key = env_key
        self.started_at = time.monotonic()
        self.worker_id: Optional[bytes] = None
        self.address: Optional[str] = None
        self.pid = proc.pid
        self.registered = threading.Event()
        self.lease_id: Optional[str] = None
        self.actor_id: Optional[bytes] = None
        self.resources: Dict[str, float] = {}
        self.pg: Optional[Tuple[bytes, int]] = None
        self.actor_incarnation: int = -1
        self.idle_since: Optional[float] = None  # set while pooled idle


class NodeDaemon:
    def __init__(self, conductor_address: str,
                 resources: Optional[Dict[str, float]] = None,
                 host: str = "127.0.0.1",
                 object_store_bytes: Optional[int] = None,
                 is_head: bool = False,
                 session_dir: Optional[str] = None,
                 env_vars: Optional[Dict[str, str]] = None,
                 tpu_slice: Optional[dict] = None):
        from ray_tpu.core.ids import NodeID
        self.node_id = NodeID.from_random().binary()
        self.conductor_address = conductor_address
        self.is_head = is_head
        self._env_vars = dict(env_vars or {})
        if resources is None:
            import multiprocessing
            resources = {"CPU": float(multiprocessing.cpu_count())}
        resources = dict(resources)
        # Slice membership: advertised to the conductor so slice-granular
        # placement groups can demand ICI contiguity (SURVEY.md §7 phase 4).
        if tpu_slice is None and resources.get("TPU", 0) > 0:
            try:
                from ray_tpu.tpu.topology import detect_slice
                tpu_slice = detect_slice()
            except Exception:
                tpu_slice = None
        self.tpu_slice = tpu_slice
        if tpu_slice is not None:
            # Typed per-generation resource next to the generic TPU count
            # (lets tasks target a generation, tpu_resources() role). Added
            # before total/_avail split so it is actually leasable.
            gen_key = f"TPU-{tpu_slice['generation']}"
            resources.setdefault(gen_key, resources.get("TPU", 0.0))
        self.total_resources = dict(resources)
        self._avail = dict(resources)
        self._lock = lockcheck.named_lock("daemon.state")
        self._cv = threading.Condition(self._lock)
        self._owns_session_dir = session_dir is None
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="rtpu-session-")
        os.makedirs(self.session_dir, exist_ok=True)
        # Hygiene: claim this session (so the sweep knows it's live) and
        # reclaim whatever dead sessions left behind before allocating shm.
        from ray_tpu.cluster import hygiene
        hygiene.write_pidfile(self.session_dir)
        try:
            hygiene.sweep_stale()
        except Exception:
            pass  # best-effort; never block startup
        # --- object store (one shmstored per node) ---
        self.store_prefix = f"rtpu-{self.node_id.hex()[:8]}-"
        self.store_socket = os.path.join(
            self.session_dir, f"store-{self.node_id.hex()[:8]}.sock")
        spill_dir = os.path.join(self.session_dir, "spill")
        os.makedirs(spill_dir, exist_ok=True)
        if object_store_bytes is None:
            object_store_bytes = int(
                config.get("object_store_memory_mb")) << 20
        self.store_proc = object_client.start_store(
            self.store_socket, object_store_bytes, self.store_prefix,
            spill_dir=spill_dir)
        self.store = object_client.ShmClient(self.store_socket,
                                             self.store_prefix)
        # Daemon-owned ObjectPlane for r16 broadcast legs (pull_object
        # RPC); built lazily — most daemons never serve one.
        self._bcast_plane = None
        self._bcast_plane_lock = threading.Lock()
        # --- workers ---
        self._workers: Dict[str, _Worker] = {}     # token -> worker
        self._idle: Dict[str, deque] = {}          # env_key -> tokens
        self._leases: Dict[str, _Worker] = {}      # lease_id -> worker
        self._bundles: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self._bundle_state: Dict[Tuple[bytes, int], str] = {}  # PREPARED|COMMITTED
        self._bundle_used: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self._pending_demand: List[Dict[str, float]] = []
        self._pending_death_reports: List[dict] = []
        self._prestarting = 0
        # Worker zygote (fork server): started lazily on the first
        # default-env spawn; None until then, False after a failed start
        # (permanent fallback to subprocess spawn).
        self._zygote_proc = None
        self._zygote_socket = os.path.join(
            self.session_dir, f"zygote-{self.node_id.hex()[:8]}.sock")
        self._zygote_lock = threading.Lock()
        self._infeasible_recent: Dict[tuple, float] = {}
        self._actor_start_pool = None
        self._stopped = False
        self._jobs: Dict[str, dict] = {}   # submission_id -> {proc, log, ...}
        # In-progress sender-initiated pushes (push_manager.h receive side).
        self._push_partial: Dict[bytes, dict] = {}
        self._push_lock = threading.Lock()
        # Compiled-graph channel forwarder: attached shm writers for rings
        # whose reader lives on this node (rpc_channel_write).
        self._chan_writers: Dict[bytes, Any] = {}
        self._chan_lock = threading.Lock()
        # Chunk-serve load counters, piggybacked on object_info so pullers
        # spread a broadcast across the least-loaded holders.
        self._serve_lock = threading.Lock()
        self._serving_chunks = 0   # fetch_chunk handlers in flight
        self._served_chunks = 0    # cumulative chunks served
        # Chunk-serve view cache: oid -> [pinned view, last_use]. A 100MB
        # pull fetches ~13 chunks; re-running get_pinned per chunk costs a
        # store round trip + a fresh 100MB mmap + its page-fault storm
        # each time. Entries idle >5s are dropped by the reap loop (the
        # pin releases once the last reply frame holding a slice is GC'd).
        self._serve_views: Dict[bytes, list] = {}
        # Remote pins taken by same-host shm-direct pulls: oid -> [count,
        # last_touch]. Reaped after 60s so a crashed puller can't block
        # deletion/recycling of the segment forever.
        self._remote_pins: Dict[bytes, list] = {}
        self.server = RpcServer(self, host=host)
        self.address = self.server.address
        reg = get_client(conductor_address).call(
            "register_node", node_id=self.node_id, address=self.address,
            resources=self.total_resources, store_socket=self.store_socket,
            is_head=is_head, tpu_slice=self.tpu_slice)
        self._conductor_epoch = (reg or {}).get("epoch")
        # Flight recorder: the daemon ships its ring delta piggybacked on
        # the heartbeat (no second periodic conductor connection). In head
        # mode the driver's _finish_init upgrades this same process with a
        # background flusher.
        _events.configure(self.node_id, conductor_address,
                          start_flusher=False)
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True, name="daemon-hb")
        self._hb_thread.start()
        self._reap_thread = threading.Thread(target=self._reap_loop,
                                             daemon=True, name="daemon-reap")
        self._reap_thread.start()
        self._prestart_thread = threading.Thread(
            target=self._prestart_loop, daemon=True, name="daemon-prestart")
        self._prestart_thread.start()
        # Pre-warm the fork server so the first worker/actor burst doesn't
        # pay its ~0.3s import boot inline.
        threading.Thread(target=self._ensure_zygote, daemon=True,
                         name="zygote-warm").start()
        self._log_thread = threading.Thread(target=self._log_monitor_loop,
                                            daemon=True, name="daemon-logs")
        self._log_thread.start()
        # OOM protection (memory_monitor.h:52 + worker_killing_policy.h:34)
        self._oom_monitor = None
        self._last_oom_kill = 0.0
        threshold = config.get("memory_usage_threshold")
        if threshold > 0:
            from ray_tpu.cluster import memory_monitor as mm
            self._oom_monitor = mm.MemoryMonitor(
                threshold, self._on_memory_pressure,
                usage_fn=mm.system_memory_usage_fraction,
                period_s=config.get("memory_monitor_refresh_ms") / 1000.0)
        # --- coordinated spill manager (local_object_manager.h role) ---
        # Watches store stats at the memory-monitor cadence; past the
        # spill threshold it writes cold unreferenced primaries through
        # the spill backend, reports URLs to the conductor (so the copy
        # survives this node), then evicts the shm copy.
        self._spill_backend = None
        self._spilled: Dict[bytes, tuple] = {}   # oid -> (url, size)
        self._spill_lock = threading.Lock()      # registry
        self._spill_write_lock = threading.Lock()  # one spiller at a time
        self._num_spilled = 0
        self._num_restored_serves = 0
        self._spill_thread = None
        if config.get("object_store_spill_threshold") > 0:
            from ray_tpu.cluster.spill import SpillBackend
            root = config.get("object_spill_dir") or os.path.join(
                self.session_dir, "spill-coord")
            try:
                self._spill_backend = SpillBackend(root)
            except Exception:
                self._spill_backend = None  # bad root: spilling disabled
            if self._spill_backend is not None:
                self._spill_thread = threading.Thread(
                    target=self._spill_loop, daemon=True,
                    name="daemon-spill")
                self._spill_thread.start()

    def _on_memory_pressure(self, usage: float) -> None:
        """Kill one worker per pressure event (rate-limited): retriable
        task workers first, newest first — the submitter's existing
        fault-tolerance path retries the killed lease's tasks, which is
        the whole point (die-with-retry beats the OS OOM killer taking
        the daemon down)."""
        from ray_tpu.cluster.memory_monitor import (WorkerKillingPolicy,
                                                    process_rss_bytes)
        now = time.monotonic()
        if now - self._last_oom_kill < 1.0:
            return
        with self._lock:
            candidates = [
                {"pid": w.pid, "worker": w,
                 "retriable": w.actor_id is None,
                 "started_at": w.started_at}
                for w in self._workers.values()
                if w.lease_id is not None or w.actor_id is not None]
        victim = WorkerKillingPolicy.pick(candidates)
        if victim is None:
            return
        self._last_oom_kill = now
        w = victim["worker"]
        try:
            get_client(self.conductor_address).call("push_logs", lines=[{
                "node": self.node_id.hex()[:8], "worker": "daemon",
                "line": f"OOM monitor: usage {usage:.2f} >= threshold; "
                        f"killing worker pid={w.pid} "
                        f"(rss={process_rss_bytes(w.pid) >> 20}MB, "
                        f"retriable={victim['retriable']})"}])
        except Exception:
            pass
        try:
            get_client(self.conductor_address).call(
                "report_event", severity="WARNING",
                source=f"daemon-{self.node_id.hex()[:8]}",
                event_type="OOM_WORKER_KILLED",
                message=f"memory usage {usage:.2f} over threshold; killed "
                        f"worker pid={w.pid} "
                        f"(retriable={victim['retriable']})",
                metadata={"pid": w.pid, "usage": usage,
                          "retriable": victim["retriable"]})
        except Exception:
            pass
        self._kill_worker(w)  # reaper reports lease/actor death

    # ------------------------------------------------------------------
    # coordinated spilling (parity: local_object_manager.h:61 — the
    # raylet component that spills primary copies past a usage threshold
    # and reports URLs so restores survive this node's death)
    # ------------------------------------------------------------------
    def _spill_loop(self) -> None:
        while not self._stopped:
            time.sleep(config.get("memory_monitor_refresh_ms") / 1000.0)
            try:
                self._maybe_spill()
            except Exception:
                pass  # store restarting / shutdown race: next tick retries

    def _maybe_spill(self) -> int:
        threshold = config.get("object_store_spill_threshold")
        if threshold <= 0 or self._spill_backend is None or self._stopped:
            return 0
        st = self.store.stats()
        cap = st.get("capacity", 0) or 1
        used = st.get("used", 0)
        if used / cap < threshold:
            return 0
        # Spill back down to the threshold in one pass (the high/low
        # watermark collapsed: the threshold is both trigger and target).
        return self._spill_bytes(max(int(used - threshold * cap), 1))

    def _spill_bytes(self, want: int) -> int:
        """Spill cold unreferenced sealed primaries until ~``want`` shm
        bytes are freed. Write-through ordering: backend write + conductor
        URL report happen BEFORE the shm copy is evicted, so there is
        never a moment with zero durable copies. Returns bytes freed."""
        if self._spill_backend is None:
            return 0
        freed = 0
        with self._spill_write_lock:
            try:
                cands = self.store.spill_candidates(want)
            except Exception:
                return 0
            for oid, size in cands:
                if freed >= want or self._stopped:
                    break
                with self._spill_lock:
                    have_copy = oid in self._spilled
                if not have_copy:
                    view = self.store.get(oid, timeout=0.0)
                    if view is None:
                        continue  # deleted since the candidate scan
                    try:
                        fault_plane.fire("object.spill.write", oid=oid,
                                         size=size)
                        url = self._spill_backend.write(oid, view)
                    except Exception:
                        self.store.release(oid)
                        continue  # backend write failed: keep shm copy
                    self.store.release(oid)
                    with self._spill_lock:
                        self._spilled[oid] = (url, size)
                        self._num_spilled += 1
                    _events.emit("object.spill.write", oid.hex(),
                                 value=float(size))
                    try:
                        get_client(self.conductor_address).call(
                            "add_spilled", oid=oid, url=url, size=size)
                    except Exception:
                        pass  # re-advertised by the heartbeat epoch replay
                # Durable copy exists: drop the shm copy. A refusal
                # (re-pinned since the scan) is fine — dual copies are
                # legal, the spill copy just waits for the next pass.
                try:
                    fault_plane.fire("object.evict", oid=oid)
                except Exception:
                    continue
                got = self.store.evict(oid)
                if got:
                    freed += got
                    _events.emit("object.evict", oid.hex(),
                                 value=float(got))
        return freed

    def rpc_spill_request(self, want_bytes: int) -> dict:
        """Put-side backpressure (spill-then-admit): an ObjectPlane whose
        create hit ST_OOM asks for room instead of failing the put."""
        if self._spill_backend is None:
            return {"freed": 0}
        return {"freed": self._spill_bytes(max(int(want_bytes), 1))}

    def _drop_spilled(self, oid: bytes) -> None:
        """Forget + delete this node's spill copy (object freed)."""
        with self._spill_lock:
            ent = self._spilled.pop(oid, None)
        if ent is not None:
            from ray_tpu.cluster import spill as _spill
            _spill.delete_url(ent[0])

    def _read_spilled_chunk(self, oid: bytes, offset: int,
                            size: int) -> Optional[bytes]:
        """Serve a chunk of an object this daemon spilled straight from
        the spill file — no shm re-inflation (a remote pull of a cold
        object must not evict warm objects on THIS node to make room)."""
        with self._spill_lock:
            ent = self._spilled.get(oid)
        if ent is None:
            return None
        from ray_tpu.cluster import spill as _spill
        fault_plane.fire("object.spill.restore", oid=oid, offset=offset)
        path = _spill.local_path(ent[0])
        try:
            if path is not None:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(size)
            else:
                data = _spill.read_url(ent[0])[offset:offset + size]
        except Exception:
            return None
        with self._spill_lock:
            self._num_restored_serves += 1
        return data

    # ------------------------------------------------------------------
    # heartbeat / membership
    # ------------------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        cli = get_client(self.conductor_address)
        while not self._stopped:
            with self._lock:
                avail = dict(self._avail)
                demand = [dict(d) for d in self._pending_demand]
            try:
                resp = cli.call("heartbeat", node_id=self.node_id,
                                resources_available=avail,
                                pending_demand=demand,
                                events=_events.heartbeat_payload())
            except Exception:
                time.sleep(float(config.get("health_check_period_s")))
                continue
            epoch = resp.get("epoch")
            if resp.get("reregister") or (
                    epoch is not None and epoch != self._conductor_epoch):
                # Conductor restarted (new epoch) or lost us: re-register
                # and re-advertise this node's volatile state — its store
                # inventory (the object directory does not persist;
                # persistence.py docstring).
                try:
                    reg = cli.call(
                        "register_node", node_id=self.node_id,
                        address=self.address,
                        resources=self.total_resources,
                        store_socket=self.store_socket,
                        is_head=self.is_head, tpu_slice=self.tpu_slice)
                    oids = self.store.list_objects()
                    if oids:
                        cli.call("add_object_locations", oids=oids,
                                 node_id=self.node_id)
                    # Spill URLs are volatile conductor state too: replay
                    # them so restores survive a conductor failover.
                    with self._spill_lock:
                        spilled = dict(self._spilled)
                    for soid, (url, size) in spilled.items():
                        cli.call("add_spilled", oid=soid, url=url,
                                 size=size)
                    # Commit the epoch only once the WHOLE re-advertisement
                    # landed — a half-failed attempt must re-run next beat.
                    self._conductor_epoch = reg.get("epoch", epoch)
                except Exception:
                    pass
            self._flush_pending_death_reports(cli)
            time.sleep(float(config.get("health_check_period_s")))

    def _flush_pending_death_reports(self, cli) -> None:
        """Actor-death reports that failed (conductor downtime) retry on
        every heartbeat: with a persistent conductor a lost report would
        otherwise leave a journal-restored actor ALIVE at a dead address
        forever."""
        with self._lock:
            pending, self._pending_death_reports = \
                self._pending_death_reports, []
        for report in pending:
            try:
                cli.call("report_actor_death", **report)
            except Exception:
                with self._lock:
                    self._pending_death_reports.append(report)

    # ------------------------------------------------------------------
    # worker pool (parity: worker_pool.h:156)
    # ------------------------------------------------------------------
    def _env_key_of(self, runtime_env: Optional[dict]) -> str:
        from ray_tpu.runtime_env import env_fingerprint
        return env_fingerprint(runtime_env)

    def _worker_base_env(self) -> Dict[str, str]:
        """Env shared by every default-env worker (and the zygote).

        Workers must not grab the TPU chip the trainer uses: plain task
        workers run on CPU unless a lease/runtime_env says otherwise, and
        CPU workers skip the TPU-plugin registration the image's
        sitecustomize performs at interpreter start (it imports jax, ~2s
        — spawn-to-register must stay well under the reaper's dead-worker
        detection latency, worker_pool.h:156's prestart rationale)."""
        env = dict(os.environ)
        env.update(self._env_vars)
        # Ship live system-config overrides (worker_main.load_from_env
        # applies them): a chaos plan or flag flip set before the spawn
        # reaches every child worker, not just in-process planes.
        env.update(config.propagation_env())
        env.setdefault("JAX_PLATFORMS",
                       env.get("RTPU_WORKER_JAX_PLATFORMS", "cpu"))
        if env.get("JAX_PLATFORMS") == "cpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
        return env

    def _ensure_zygote(self):
        """Start (once) the fork server for default-env workers. Returns
        the zygote Popen, or None when unavailable (fallback: subprocess
        spawn). The zygote pays the ~0.25s worker-import cost once; each
        subsequent worker is a fork (~15ms) — the difference between 3/s
        and 25+/s actor creation on one host."""
        with self._zygote_lock:
            if self._zygote_proc is False:
                return None
            if self._zygote_proc is not None:
                if self._zygote_proc.poll() is None:
                    return self._zygote_proc
                self._zygote_proc = None  # died; restart below
            try:
                proc = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.cluster.worker_zygote",
                     "--socket", self._zygote_socket],
                    env=self._worker_base_env(),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True)
                # Bounded handshake: a zygote hung in its pre-imports must
                # not wedge every _spawn_worker behind _zygote_lock — time
                # out, kill it, and fall back to subprocess spawn forever.
                import select
                ready, _, _ = select.select([proc.stdout], [], [], 60.0)
                line = proc.stdout.readline() if ready else ""
                if not line.startswith("ZYGOTE_READY"):
                    proc.kill()
                    self._zygote_proc = False
                    return None
                self._zygote_proc = proc
                return proc
            except Exception:
                self._zygote_proc = False
                return None

    def _fork_worker(self, argv: List[str], env: Dict[str, str],
                     log_path: str) -> Optional[_ForkedProc]:
        if self._ensure_zygote() is None:
            return None
        import json
        import socket as _socket
        try:
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.settimeout(10.0)
            s.connect(self._zygote_socket)
            # Only the DELTA env rides the request (the zygote already runs
            # under _worker_base_env); sending a full environ would mostly
            # be noise but is harmless — the child applies it wholesale.
            s.sendall(json.dumps({"argv": argv, "env": env, "cwd": None,
                                  "log": log_path}).encode() + b"\n")
            data = b""
            while not data.endswith(b"\n"):
                chunk = s.recv(4096)
                if not chunk:
                    return None
                data += chunk
            s.close()
            return _ForkedProc(json.loads(data)["pid"])
        except Exception:
            return None

    def _spawn_worker(self, env_key: str,
                      runtime_env: Optional[dict]) -> _Worker:
        if self._stopped:
            # Teardown fence: stop() is about to (or already did) rmtree the
            # session dir; spawning into it would die on the log-file open
            # with an unhandled FileNotFoundError in the start thread.
            raise _DaemonStopping("node daemon is stopping")
        fault_plane.fire("daemon.worker.spawn", env_key=env_key)
        token = uuid.uuid4().hex
        if env_key == "" and not runtime_env:
            # Default-env workers fork from the zygote when possible.
            argv = ["--conductor", self.conductor_address,
                    "--daemon", self.address,
                    "--store-socket", self.store_socket,
                    "--store-prefix", self.store_prefix,
                    "--node-id", self.node_id.hex(),
                    "--token", token]
            log_path = os.path.join(self.session_dir,
                                    f"worker-{token[:8]}.out")
            # Delta env over the zygote's baseline: overrides set AFTER the
            # zygote started (a freshly loaded fault plan) still reach the
            # forked child.
            proc = self._fork_worker(argv, config.propagation_env(),
                                     log_path)
            if proc is not None:
                w = _Worker(proc, token, env_key)
                with self._lock:
                    self._workers[token] = w
                return w
        env = self._worker_base_env()
        if runtime_env and runtime_env.get("env_vars"):
            env.update({str(k): str(v)
                        for k, v in runtime_env["env_vars"].items()})
        if runtime_env and runtime_env.get("py_modules"):
            # content-addressed unpack once per module version, then
            # PYTHONPATH (runtime-env agent role, _private/runtime_env/)
            from ray_tpu.runtime_env import unpack_py_modules
            extra = unpack_py_modules(
                runtime_env["py_modules"],
                os.path.join(self.session_dir, "py_modules"))
            if extra:
                prev = env.get("PYTHONPATH", "")
                env["PYTHONPATH"] = (extra + os.pathsep + prev) if prev \
                    else extra
        # _worker_base_env defaulted JAX_PLATFORMS=cpu and dropped the TPU
        # plugin registration; a runtime_env that explicitly requests a
        # non-CPU platform gets the registration back (from the daemon's
        # configured env first — it overrides the inherited environ in
        # _worker_base_env too).
        pool_ips = self._env_vars.get("PALLAS_AXON_POOL_IPS") or \
            os.environ.get("PALLAS_AXON_POOL_IPS")
        if env.get("JAX_PLATFORMS") != "cpu" and pool_ips:
            env.setdefault("PALLAS_AXON_POOL_IPS", pool_ips)
        cwd = None
        if runtime_env and runtime_env.get("working_dir"):
            cwd = runtime_env["working_dir"]
        py_exe = sys.executable
        if runtime_env and runtime_env.get("pip"):
            # venv per pip-spec hash (runtime-env agent role); the worker
            # runs on the venv interpreter so its installs are importable.
            from ray_tpu.runtime_env import ensure_pip_env
            py_exe = ensure_pip_env(runtime_env["pip"], self.session_dir)
            # ray_tpu itself rides PYTHONPATH into the venv interpreter.
            repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            prev = env.get("PYTHONPATH", "")
            if repo_root not in prev.split(os.pathsep):
                env["PYTHONPATH"] = (repo_root + os.pathsep + prev) if prev \
                    else repo_root
        try:
            out = open(os.path.join(
                self.session_dir, f"worker-{token[:8]}.out"), "wb")
        except FileNotFoundError:
            # Session dir vanished between the _stopped check and the open:
            # teardown won the race; refuse to spawn into a dead session.
            raise _DaemonStopping("session dir removed (daemon stopping)")
        proc = subprocess.Popen(
            [py_exe, "-m", "ray_tpu.cluster.worker_main",
             "--conductor", self.conductor_address,
             "--daemon", self.address,
             "--store-socket", self.store_socket,
             "--store-prefix", self.store_prefix,
             "--node-id", self.node_id.hex(),
             "--token", token],
            env=env, cwd=cwd,
            stdout=out,
            stderr=subprocess.STDOUT)
        w = _Worker(proc, token, env_key)
        with self._lock:
            self._workers[token] = w
        return w

    def rpc_register_worker(self, token: str, worker_id: bytes,
                            address: str, pid: int) -> dict:
        with self._cv:
            w = self._workers.get(token)
            if w is None:
                return {"ok": False}
            w.worker_id = worker_id
            w.address = address
            w.registered.set()
            self._cv.notify_all()
        return {"ok": True, "node_id": self.node_id}

    def _checkout_worker(self, env_key: str, runtime_env: Optional[dict],
                         timeout: float = 30.0,
                         idle_only: bool = False) -> Optional[_Worker]:
        if runtime_env and runtime_env.get("pip"):
            # Materialize the venv BEFORE the spawn deadline starts: first
            # builds can take longer than the checkout budget, and the
            # cached hit on the spawn path below is then instant.
            from ray_tpu.runtime_env import ensure_pip_env
            ensure_pip_env(runtime_env["pip"], self.session_dir)
        while True:
            with self._lock:
                q = self._idle.get(env_key)
                w = None
                while q:
                    token = q.popleft()
                    cand = self._workers.get(token)
                    if cand is not None and cand.proc.poll() is None:
                        w = cand
                        w.idle_since = None
                        break
            if w is None:
                break
            # poll() can lag a dying process (a worker that just os._exit'd
            # may not be reaped yet); a ping confirms the RPC server is
            # actually accepting before we hand the lease out.
            try:
                get_client(w.address).call("ping", _timeout=2.0)
                return w
            except Exception:
                from ray_tpu.cluster.protocol import drop_client
                drop_client(w.address)
                self._kill_worker(w)
        if idle_only:
            # Multi-grant extras: only instant (pooled/recycled) workers
            # qualify — a spawn would serialize ~200ms boots inside one
            # lease RPC and blow the caller's timeout.
            return None
        # No reusable idle worker: spawn, and keep respawning within the
        # deadline if a fresh worker dies before registering (under a chaos
        # kill storm every starting process is a target; one attempt per
        # lease would livelock the whole submitter).
        deadline = time.monotonic() + timeout
        while True:
            try:
                w = self._spawn_worker(env_key, runtime_env)
            except _DaemonStopping:
                return None
            while True:
                if w.registered.wait(0.05):
                    return w
                if w.proc.poll() is not None:
                    break  # died pre-registration; respawn below
                if time.monotonic() >= deadline:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                    with self._lock:
                        self._workers.pop(w.token, None)
                    return None
            with self._lock:
                self._workers.pop(w.token, None)
            if time.monotonic() >= deadline:
                return None

    def _checkin_worker(self, w: _Worker, cap: Optional[int] = None) -> bool:
        """Return ``w`` to the idle pool; True if pooled, False if killed.
        ``cap`` overrides worker_pool_max_size (actor recycling pools far
        deeper than the spawn-side task cap)."""
        if cap is None:
            cap = config.get("worker_pool_max_size")
        with self._lock:
            if self._stopped or w.proc.poll() is not None:
                self._workers.pop(w.token, None)
                return False
            w.lease_id = None
            w.resources = {}
            w.pg = None
            pool = self._idle.setdefault(w.env_key, deque())
            if len(pool) < cap:
                w.idle_since = time.monotonic()
                pool.append(w.token)
                return True
        self._kill_worker(w)
        return False

    def _kill_worker(self, w: _Worker) -> None:
        with self._lock:
            self._workers.pop(w.token, None)
        try:
            w.proc.kill()
        except OSError:
            pass

    def _prestart_loop(self) -> None:
        """Prestart workers against lease backlog (parity:
        node_manager.cc:1869 PrestartWorkers): while lease requests queue
        on resources/spawns, warm spare workers concurrently so grants
        don't serialize behind one-at-a-time process startup."""
        while not self._stopped:
            time.sleep(0.25)
            with self._lock:
                # Only FEASIBLE demand is backlog (infeasible shapes sit in
                # _pending_demand for the autoscaler; warming workers for
                # them would idle forever), and only the default-env pool
                # is prestartable (runtime-env workers need the lease's
                # env; the reference prestarts default workers the same
                # way) — so compare against _idle[""] alone.
                backlog = sum(
                    1 for d in self._pending_demand
                    if all(self.total_resources.get(k, 0.0) + 1e-9 >= v
                           for k, v in d.items()))
                idle = len(self._idle.get("", ()))
                cap = min(config.get("worker_pool_max_size"),
                          int(self.total_resources.get("CPU", 0)) or 1)
                # worker_pool_min_size keeps a warm floor of default-env
                # workers independent of backlog (boot-time prestart).
                floor = int(config.get("worker_pool_min_size"))
                want = min(max(backlog, floor) - idle - self._prestarting,
                           cap - len(self._workers))
                if want > 0:
                    self._prestarting += want
            for _ in range(max(0, want)):
                threading.Thread(target=self._prestart_one, daemon=True,
                                 name="worker-prestart").start()

    def _prestart_one(self) -> None:
        try:
            w = self._spawn_worker("", None)
            if w.registered.wait(15.0) and w.proc.poll() is None:
                with self._lock:
                    w.idle_since = time.monotonic()
                    self._idle.setdefault("", deque()).append(w.token)
                with self._cv:
                    self._cv.notify_all()
            else:
                self._kill_worker(w)
        except Exception:
            pass
        finally:
            with self._lock:
                self._prestarting -= 1

    def _reap_loop(self) -> None:
        """Detect dead workers: fail their leases / report actor death."""
        while not self._stopped:
            time.sleep(0.2)
            # Abandoned partial pushes (sender died mid-stream) are dropped
            # so a fresh push or pull can recreate the entry.
            with self._push_lock:
                now = time.monotonic()
                stale = [(o, st) for o, st in self._push_partial.items()
                         if now - st["ts"] > 30.0]
                for oid, _ in stale:
                    self._push_partial.pop(oid, None)
            for oid, st in stale:  # store I/O outside the push-dict lock
                try:
                    with st["lock"]:   # never close under a mid-flight
                        if st["buf"] is not None:  # chunk write
                            st["buf"].close()
                    self.store.delete(oid)
                except Exception:
                    pass
            # Idle chunk-serve views: dropping the entry lets the pinned
            # mapping GC (the finalize queues the store release), so the
            # object becomes deletable/evictable again.
            with self._serve_lock:
                now = time.monotonic()
                for oid in [o for o, e in self._serve_views.items()
                            if now - e[1] > 5.0]:
                    self._serve_views.pop(oid, None)
                leaked = [o for o, e in self._remote_pins.items()
                          if now - e[1] > 60.0]
                for oid in leaked:
                    self._remote_pins.pop(oid, None)
            for oid in leaked:  # puller died mid shm-direct copy
                try:
                    self.store.release(oid)
                except Exception:
                    pass
            # Idle-pool reaping: pooled workers idle past
            # worker_idle_timeout_s are killed oldest-first, keeping the
            # worker_pool_min_size warm floor in the default-env pool.
            idle_timeout = float(config.get("worker_idle_timeout_s"))
            expired: List[_Worker] = []
            if idle_timeout > 0:
                floor = int(config.get("worker_pool_min_size"))
                with self._lock:
                    now = time.monotonic()
                    for env_key, q in self._idle.items():
                        keep = floor if env_key == "" else 0
                        while len(q) > keep:
                            w = self._workers.get(q[0])
                            if w is None:
                                q.popleft()
                                continue
                            if w.idle_since is not None and \
                                    now - w.idle_since > idle_timeout:
                                q.popleft()
                                expired.append(w)
                            else:
                                break  # leftmost is the longest-idle
            for w in expired:
                self._kill_worker(w)
            dead: List[_Worker] = []
            with self._lock:
                for w in list(self._workers.values()):
                    if w.proc.poll() is not None:
                        dead.append(w)
                        self._workers.pop(w.token, None)
                        for q in self._idle.values():
                            try:
                                q.remove(w.token)
                            except ValueError:
                                pass
            for w in dead:
                exit_code = w.proc.returncode
                # Reap the dead worker's metrics snapshot: its KV entry is
                # keyed (node, pid) and nothing will ever refresh it again
                # (stale snapshots otherwise pollute /metrics forever).
                try:
                    get_client(self.conductor_address).call(
                        "kv_del", ns="metrics",
                        key=f"proc-{self.node_id.hex()}-{w.pid}".encode())
                except Exception:
                    pass
                if w.lease_id is not None:
                    self._release_lease_resources(w)
                if w.actor_id is not None:
                    report = {
                        "actor_id": w.actor_id,
                        "reason": f"worker process died (exit {exit_code})",
                        "incarnation": w.actor_incarnation,
                    }
                    # Free the crashed actor's reservation BEFORE reporting
                    # the death: the conductor reacts by rescheduling the
                    # restart incarnation, which on a full node can only
                    # place if the dead incarnation's resources are back in
                    # the pool (a leak here starved every restart for the
                    # whole 30s placement window, then failed the actor).
                    self._release_actor_resources(w)
                    try:
                        get_client(self.conductor_address).call(
                            "report_actor_death", **report)
                    except Exception:
                        # conductor down: the heartbeat loop re-delivers
                        with self._lock:
                            self._pending_death_reports.append(report)

    # ------------------------------------------------------------------
    # leases (parity: HandleRequestWorkerLease node_manager.cc:1847)
    # ------------------------------------------------------------------
    def _resource_pool_for(self, strategy: Any):
        """Returns (get_avail, take, give) closures for node or bundle pool."""
        if isinstance(strategy, dict) and strategy.get("type") == "pg":
            key = (strategy["pg_id"], max(0, strategy.get("bundle_index", 0)))
            def avail():
                reserved = self._bundles.get(key, {})
                used = self._bundle_used.setdefault(key, {})
                return {k: reserved.get(k, 0.0) - used.get(k, 0.0)
                        for k in reserved}
            def take(res):
                used = self._bundle_used.setdefault(key, {})
                for k, v in res.items():
                    used[k] = used.get(k, 0.0) + v
            def give(res):
                used = self._bundle_used.setdefault(key, {})
                for k, v in res.items():
                    used[k] = used.get(k, 0.0) - v
            return avail, take, give
        def avail():
            return self._avail
        def take(res):
            for k, v in res.items():
                self._avail[k] = self._avail.get(k, 0.0) - v
        def give(res):
            for k, v in res.items():
                self._avail[k] = self._avail.get(k, 0.0) + v
        return avail, take, give

    def rpc_request_lease(self, resources: Dict[str, float],
                          runtime_env: Optional[dict] = None,
                          strategy: Any = None,
                          wait_timeout: float = 5.0,
                          idle_only: bool = False) -> dict:
        """Grant a worker lease, queue until resources free (bounded wait),
        or reply infeasible so the caller spills to another node."""
        fault_plane.fire("daemon.lease.grant", idle_only=idle_only)
        resources = {k: v for k, v in resources.items() if v > 0}
        avail_fn, take, _ = self._resource_pool_for(strategy)
        deadline = time.monotonic() + wait_timeout
        demand_entry = dict(resources)
        with self._cv:
            # Infeasible on this node entirely -> immediate spillback hint.
            if not isinstance(strategy, dict) or strategy.get("type") != "pg":
                if any(self.total_resources.get(k, 0.0) + 1e-9 < v
                       for k, v in resources.items()):
                    # Register infeasible-here demand for the autoscaler,
                    # deduped per shape: spillback probes repeat every few
                    # hundred ms and must not stack into phantom demand.
                    shape_key = tuple(sorted(resources.items()))
                    now = time.monotonic()
                    if now - self._infeasible_recent.get(shape_key, 0) > 1.0:
                        self._infeasible_recent[shape_key] = now
                        self._pending_demand.append(demand_entry)
                        threading.Timer(1.0, self._drop_demand,
                                        (demand_entry,)).start()
                    return {"granted": False, "infeasible": True}
            self._pending_demand.append(demand_entry)
            try:
                while True:
                    a = avail_fn()
                    if all(a.get(k, 0.0) + 1e-9 >= v
                           for k, v in resources.items()):
                        take(resources)
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {"granted": False, "infeasible": False}
                    self._cv.wait(min(remaining, 0.5))
            finally:
                try:
                    self._pending_demand.remove(demand_entry)
                except ValueError:
                    pass
        env_key = self._env_key_of(runtime_env)
        from ray_tpu.core.exceptions import RuntimeEnvSetupError
        try:
            w = self._checkout_worker(env_key, runtime_env, timeout=10.0,
                                      idle_only=idle_only)
        except RuntimeEnvSetupError as e:
            self._give_back(strategy, resources)
            return {"granted": False, "env_error": str(e)}
        if w is None:
            self._give_back(strategy, resources)
            return {"granted": False, "infeasible": False}
        lease_id = uuid.uuid4().hex
        with self._lock:
            w.lease_id = lease_id
            w.resources = resources
            if isinstance(strategy, dict) and strategy.get("type") == "pg":
                w.pg = (strategy["pg_id"], max(0, strategy.get("bundle_index", 0)))
            self._leases[lease_id] = w
        return {"granted": True, "lease_id": lease_id,
                "worker_address": w.address, "worker_pid": w.pid,
                "node_id": self.node_id}

    def rpc_request_leases(self, resources: Dict[str, float],
                           count: int = 1,
                           runtime_env: Optional[dict] = None,
                           strategy: Any = None,
                           wait_timeout: float = 5.0) -> dict:
        """Multi-grant lease request: one round-trip for up to ``count``
        leases of the same shape. The first grant may wait the full
        ``wait_timeout``; extras come only from immediately free resources
        plus already-warm (pooled/recycled) workers, so the reply never
        serializes fresh process boots inside one RPC."""
        first = self.rpc_request_lease(resources, runtime_env, strategy,
                                       wait_timeout)
        if not first.get("granted"):
            return dict(first, leases=[])
        leases = [first]
        for _ in range(max(0, count - 1)):
            extra = self.rpc_request_lease(resources, runtime_env, strategy,
                                           wait_timeout=0.0, idle_only=True)
            if not extra.get("granted"):
                break
            leases.append(extra)
        return {"granted": True, "leases": leases, "node_id": self.node_id}

    def _give_back(self, strategy: Any,
                   resources: Dict[str, float]) -> None:
        with self._cv:
            _, _, give = self._resource_pool_for(strategy)
            give(resources)
            self._cv.notify_all()

    def _drop_demand(self, entry: Dict[str, float]) -> None:
        with self._lock:
            try:
                self._pending_demand.remove(entry)
            except ValueError:
                pass

    def _release_lease_resources(self, w: _Worker) -> None:
        with self._cv:
            if w.lease_id is None:
                return
            self._leases.pop(w.lease_id, None)
            if w.pg is not None:
                used = self._bundle_used.setdefault(w.pg, {})
                for k, v in w.resources.items():
                    used[k] = used.get(k, 0.0) - v
            else:
                for k, v in w.resources.items():
                    self._avail[k] = self._avail.get(k, 0.0) + v
            w.lease_id = None
            w.resources = {}
            w.pg = None
            self._cv.notify_all()

    def rpc_return_lease(self, lease_id: str) -> None:
        with self._lock:
            w = self._leases.get(lease_id)
        if w is None:
            return
        self._release_lease_resources(w)
        self._checkin_worker(w)

    # ------------------------------------------------------------------
    # actors (conductor -> daemon -> dedicated worker)
    # ------------------------------------------------------------------
    def rpc_start_actor(self, actor_id: bytes, spec: dict,
                        incarnation: int) -> dict:
        return self.rpc_start_actors([{"actor_id": actor_id, "spec": spec,
                                       "incarnation": incarnation}])

    def rpc_start_actors(self, items: List[dict]) -> dict:
        """Wave bring-up: members run on a BOUNDED pool instead of one
        thread per request — N unbounded concurrent fork+boots thrash a
        small host (measured: a 40-actor wave boots slower in aggregate
        than 8-at-a-time). An actor whose resources aren't immediately
        free detaches to its own waiting thread so it cannot plug a pool
        slot for up to its 30s resource deadline."""
        pool = self._actor_pool()
        for item in items:
            pool.submit(self._start_actor_pooled, item["actor_id"],
                        item["spec"], item["incarnation"])
        return {"ok": True, "count": len(items)}

    def _actor_pool(self):
        with self._lock:
            if self._stopped:
                raise _DaemonStopping("node daemon is stopping")
            if self._actor_start_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._actor_start_pool = ThreadPoolExecutor(
                    max_workers=max(1, config.get("actor_start_pool_size")),
                    thread_name_prefix="start-actor")
            return self._actor_start_pool

    def _start_actor_pooled(self, actor_id: bytes, spec: dict,
                            incarnation: int) -> None:
        try:
            resources, strategy = self._actor_resources(spec)
            _, take, _ = self._resource_pool_for(strategy)
            with self._cv:
                a = self._resource_pool_for(strategy)[0]()
                ready = all(a.get(k, 0.0) + 1e-9 >= v
                            for k, v in resources.items())
                if ready:
                    take(resources)
            if ready:
                self._start_actor(actor_id, spec, incarnation,
                                  reserved=True)
            else:
                threading.Thread(
                    target=self._start_actor, daemon=True,
                    args=(actor_id, spec, incarnation),
                    name=f"start-actor-{actor_id.hex()[:8]}").start()
        except Exception:
            pass  # per-actor failures are reported inside _start_actor

    @staticmethod
    def _actor_resources(spec: dict):
        opts = spec["opts"]
        resources = {k: v for k, v in
                     opts.get("resources_req", {"CPU": 1.0}).items() if v > 0}
        return resources, opts.get("scheduling_strategy")

    def _start_actor(self, actor_id: bytes, spec: dict, incarnation: int,
                     reserved: bool = False) -> None:
        import pickle
        opts = spec["opts"]
        resources, strategy = self._actor_resources(spec)
        avail_fn, take, _ = self._resource_pool_for(strategy)
        cli = get_client(self.conductor_address)
        deadline = time.monotonic() + 30.0
        if not reserved:
            timed_out = False
            with self._cv:
                while True:
                    a = avail_fn()
                    if all(a.get(k, 0.0) + 1e-9 >= v
                           for k, v in resources.items()):
                        take(resources)
                        break
                    if time.monotonic() >= deadline:
                        timed_out = True
                        break
                    self._cv.wait(0.5)
            if timed_out:
                # The failure report is a conductor RPC (with reconnect
                # retries) — it must run OUTSIDE the daemon state lock or
                # a slow conductor freezes every lease/heartbeat path.
                try:
                    cli.call("actor_creation_failed",
                             actor_id=actor_id,
                             incarnation=incarnation,
                             error_blob=pickle.dumps(RuntimeError(
                                 "insufficient resources for actor")))
                except Exception:
                    pass
                return
        from ray_tpu.core.exceptions import RuntimeEnvSetupError
        try:
            w = self._checkout_worker(
                self._env_key_of(opts.get("runtime_env")),
                opts.get("runtime_env"))
        except RuntimeEnvSetupError as e:
            # Deterministic env failure: free the reservation and fail the
            # actor's creation (callers holding refs see the error instead
            # of a forever-PENDING actor).
            self._give_back(strategy, resources)
            try:
                cli.call("actor_creation_failed", actor_id=actor_id,
                         incarnation=incarnation,
                         error_blob=pickle.dumps(e))
            except Exception:
                pass
            return
        if w is None:
            with self._cv:
                _, _, give = self._resource_pool_for(strategy)
                give(resources)
                self._cv.notify_all()
            try:
                cli.call("actor_creation_failed", actor_id=actor_id,
                         incarnation=incarnation,
                         error_blob=pickle.dumps(RuntimeError(
                             "failed to start a worker process")))
            except Exception:
                pass
            return
        with self._lock:
            w.actor_id = actor_id
            w.actor_incarnation = incarnation
            w.resources = resources
            if isinstance(strategy, dict) and strategy.get("type") == "pg":
                w.pg = (strategy["pg_id"], max(0, strategy.get("bundle_index", 0)))
        try:
            resp = get_client(w.address).call(
                "create_actor", actor_id=actor_id, spec=spec,
                incarnation=incarnation)
        except Exception as e:
            self._release_actor_resources(w)
            self._kill_worker(w)
            # Infrastructure failure (worker process died under us) — this
            # consumes the restart FSM rather than permanently killing the
            # actor; only a user __init__ exception is terminal.
            try:
                cli.call("report_actor_death", actor_id=actor_id,
                         reason=f"actor worker unreachable during "
                                f"creation: {e}",
                         incarnation=incarnation)
            except Exception:
                pass
            return
        if not resp.get("ok"):
            # __init__ raised; the worker already reported the error to the
            # conductor — free the reservation and recycle the process.
            self._release_actor_resources(w)
            self._kill_worker(w)

    def _release_actor_resources(self, w: _Worker) -> None:
        with self._cv:
            if w.actor_id is None:
                return
            if w.pg is not None:
                used = self._bundle_used.setdefault(w.pg, {})
                for k, v in w.resources.items():
                    used[k] = used.get(k, 0.0) - v
            else:
                for k, v in w.resources.items():
                    self._avail[k] = self._avail.get(k, 0.0) + v
            w.actor_id = None
            w.resources = {}
            self._cv.notify_all()

    def rpc_actor_exited(self, actor_id: bytes,
                         recycle: bool = False) -> dict:
        """Worker notifies a clean actor kill; free resources, then either
        RECYCLE the process into the idle pool or kill it. The worker only
        offers recycle=True after fully resetting its actor state, and
        os._exit()s unless we answer recycled=True. Recycling is what makes
        repeated actor waves cheap: the next creation checks out a warm
        process instead of paying fork + interpreter boot (~200ms, the
        dominant cost of a wave on a small host)."""
        with self._lock:
            target = None
            for w in self._workers.values():
                if w.actor_id == actor_id:
                    target = w
                    break
        if target is None:
            return {"recycled": False}
        self._release_actor_resources(target)
        if (recycle and target.env_key == ""
                and config.get("actor_worker_recycle")):
            cap = max(config.get("worker_pool_max_size"),
                      config.get("actor_recycle_pool_cap"))
            if self._checkin_worker(target, cap=cap):
                with self._cv:
                    self._cv.notify_all()
                return {"recycled": True}
            return {"recycled": False}
        self._kill_worker(target)
        return {"recycled": False}

    # ------------------------------------------------------------------
    # placement-group bundles (2PC; parity placement_group_resource_manager.h)
    # ------------------------------------------------------------------
    def rpc_prepare_bundle(self, pg_id: bytes, bundle_index: int,
                           resources: Dict[str, float]) -> bool:
        key = (pg_id, bundle_index)
        with self._cv:
            if key in self._bundles:
                return True  # idempotent retry
            if any(self._avail.get(k, 0.0) + 1e-9 < v
                   for k, v in resources.items()):
                return False
            for k, v in resources.items():
                self._avail[k] = self._avail.get(k, 0.0) - v
            self._bundles[key] = dict(resources)
            self._bundle_state[key] = "PREPARED"
            return True

    def rpc_commit_bundle(self, pg_id: bytes, bundle_index: int) -> bool:
        with self._lock:
            key = (pg_id, bundle_index)
            if key not in self._bundles:
                return False
            self._bundle_state[key] = "COMMITTED"
            return True

    def rpc_return_bundle(self, pg_id: bytes, bundle_index: int) -> None:
        key = (pg_id, bundle_index)
        with self._cv:
            res = self._bundles.pop(key, None)
            self._bundle_state.pop(key, None)
            self._bundle_used.pop(key, None)
            if res:
                for k, v in res.items():
                    self._avail[k] = self._avail.get(k, 0.0) + v
            self._cv.notify_all()
        # Kill workers still running in this bundle.
        victims = []
        with self._lock:
            for w in self._workers.values():
                if w.pg == key:
                    victims.append(w)
        for w in victims:
            if w.actor_id is not None:
                try:
                    get_client(self.conductor_address).call(
                        "report_actor_death", actor_id=w.actor_id,
                        reason="placement group removed",
                        incarnation=w.actor_incarnation)
                except Exception:
                    pass
            self._kill_worker(w)

    # ------------------------------------------------------------------
    # object transfer (parity: object_manager.h:117 chunked push/pull)
    # ------------------------------------------------------------------
    def rpc_object_info(self, oid: bytes) -> dict:
        view = self.store.get(oid, timeout=0.0)
        if view is None:
            with self._spill_lock:
                ent = self._spilled.get(oid)
            if ent is not None:
                # Spilled here: fetch_chunk serves from the spill file.
                # No shm_path — same-host pullers must take the chunk
                # path too (there is no segment to map).
                return {"found": True, "size": ent[1],
                        "transfers": self._serving_chunks,
                        "served": self._served_chunks,
                        "spilled": True}
            return {"found": False, "size": 0}
        size = view.nbytes
        self.store.release(oid)
        # transfers/served: this daemon's chunk-serve load, so pullers pick
        # the least-loaded holder (object_manager location-spread role).
        # shm_path: same-host pullers copy the segment directly instead of
        # streaming chunks (object_pull_shm_direct).
        return {"found": True, "size": size,
                "transfers": self._serving_chunks,
                "served": self._served_chunks,
                "shm_path": self.store._shm_path(oid)}

    def rpc_pull_object(self, oid: bytes,
                        sources: Optional[list] = None) -> dict:
        """Pull one object into this node's store NOW (r16 broadcast leg:
        the driver coordinates a tree of these, each member pulling from
        the holder the schedule assigned via ``sources``). Falls back to
        a directory locate when no sources are given or the assigned
        source cannot serve. Reuses the plane's full windowed-pull
        machinery — shm-direct same-host copies, striping, failover and
        its fault sites all apply to a broadcast leg."""
        plane = self._pull_plane()
        if self.store.contains(oid):
            return {"ok": True, "outcome": "local"}
        outcome = "error"
        if sources:
            nodes = [{"node_id": s.get("node_id"), "address": s["address"]}
                     for s in sources]
            outcome = plane._pull_from(oid, nodes)
            if outcome == "ok":
                return {"ok": True, "outcome": "ok"}
        try:
            loc = get_client(self.conductor_address).call(
                "locate_object", oid=oid, timeout=2.0)
        except Exception:  # noqa: BLE001
            return {"ok": False, "outcome": outcome}
        nodes = [n for n in loc.get("nodes", ())
                 if n["node_id"] != self.node_id]
        if nodes:
            outcome = plane._pull_from(oid, nodes)
        if outcome != "ok" and loc.get("spilled"):
            if plane._restore_spilled(oid, loc["spilled"],
                                      int(loc.get("spilled_size") or 0)):
                outcome = "ok"
        return {"ok": outcome == "ok", "outcome": outcome}

    def _pull_plane(self):
        """Lazily-built daemon-owned ObjectPlane (broadcast legs only —
        the daemon's normal serve path never needs one)."""
        with self._bcast_plane_lock:
            if self._bcast_plane is None:
                from ray_tpu.cluster.object_plane import ObjectPlane
                self._bcast_plane = ObjectPlane(
                    self.store, self.node_id, self.conductor_address,
                    daemon_address=self.address)
            return self._bcast_plane

    def rpc_pin_object(self, oid: bytes) -> dict:
        """Hold a store reference on behalf of a same-host shm-direct
        puller, so the segment cannot be deleted or recycled while the
        puller copies it. Balanced by unpin_object; leaked pins (puller
        died mid-copy) are reaped after 60s."""
        with self._serve_lock:
            ent = self._remote_pins.get(oid)
            if ent is not None:
                ent[0] += 1
                ent[1] = time.monotonic()
                return {"ok": True}
        view = self.store.get(oid, timeout=0.0)
        if view is None:
            return {"ok": False}
        with self._serve_lock:
            ent = self._remote_pins.get(oid)
            if ent is None:
                self._remote_pins[oid] = [1, time.monotonic()]
                return {"ok": True}
            ent[0] += 1
            ent[1] = time.monotonic()
        self.store.release(oid)  # the existing entry's ref covers us
        return {"ok": True}

    def rpc_unpin_object(self, oid: bytes) -> dict:
        with self._serve_lock:
            ent = self._remote_pins.get(oid)
            if ent is None:
                return {"ok": False}
            ent[0] -= 1
            if ent[0] > 0:
                return {"ok": True}
            self._remote_pins.pop(oid, None)
        self.store.release(oid)
        return {"ok": True}

    def rpc_fetch_chunk(self, oid: bytes, offset: int, size: int):
        fault_plane.fire("daemon.chunk.serve", oid=oid, offset=offset)
        with self._serve_lock:
            self._serving_chunks += 1
            ent = self._serve_views.get(oid)
            view = None
            if ent is not None:
                ent[1] = time.monotonic()
                view = ent[0]
        try:
            if view is None:
                view = self.store.get_pinned(oid, timeout=0.0)
                if view is None:
                    chunk = self._read_spilled_chunk(oid, offset, size)
                    if chunk is not None:
                        with self._serve_lock:
                            self._served_chunks += 1
                        return chunk
                    raise KeyError(f"object {oid.hex()} not in store")
                with self._serve_lock:
                    if oid not in self._serve_views \
                            and len(self._serve_views) < 8:
                        self._serve_views[oid] = [view, time.monotonic()]
            # Zero-copy serve: the RPC reply's out-of-band frame path
            # sendmsg()s straight from the pinned shm mapping — no bytes()
            # materialization per chunk. The pin releases when the reply
            # frame (and its view) is garbage collected after send.
            buf = pickle.PickleBuffer(view[offset:offset + size])
            with self._serve_lock:
                self._served_chunks += 1
            return buf
        finally:
            with self._serve_lock:
                self._serving_chunks -= 1

    def rpc_push_chunk(self, oid: bytes, offset: int, total: int,
                       chunk: bytes, stream: Optional[str] = None) -> dict:
        """Receive one chunk of a sender-initiated push (push_manager.h
        role). The sender keeps a WINDOW of chunks pipelined, and the
        server dispatches pipelined frames on a pool — so chunks of one
        stream legally arrive OUT OF ORDER. The first to arrive creates
        the buffer; completion is by byte count, and the completing chunk
        seals + registers the location. Each push carries a
        sender-generated ``stream`` id: a chunk from a DIFFERENT stream
        than the in-progress one is rejected without touching that push
        (two senders racing must not destroy each other's partial writes).
        A concurrent local pull of the same object wins ties (create
        raises already-exists → reject the push; pull is the correctness
        path)."""
        with self._push_lock:  # guards the dict only — never I/O
            st = self._push_partial.get(oid)
            if st is None:
                # Claim the oid with an empty entry; the store create
                # happens below, outside this lock (store I/O must not
                # serialize every concurrent push through one mutex).
                st = self._push_partial[oid] = {
                    "buf": None, "got": set(), "bytes": 0, "total": total,
                    "stream": stream, "ts": time.monotonic(),
                    "lock": threading.Lock()}
            elif st.get("stream") != stream:
                return {"reject": True}  # another sender's push in progress
        with st["lock"]:
            if st["buf"] is None:
                if self.store.contains(oid):
                    with self._push_lock:
                        self._push_partial.pop(oid, None)
                    return {"done": True}
                try:
                    st["buf"] = self.store.create_writer(oid, total)
                except Exception:
                    with self._push_lock:
                        self._push_partial.pop(oid, None)
                    return {"done": True}  # being written by a pull
            if st["total"] != total:
                # Same stream claims a different object size (sender died
                # and resumed under the same id): abort the push and
                # DELETE the unsealed entry — an orphaned CREATED object
                # would wedge every future pull (create→already-exists,
                # get→never sealed).
                with self._push_lock:
                    self._push_partial.pop(oid, None)
                st["buf"].close()
                try:
                    self.store.delete(oid)
                except Exception:
                    pass
                return {"reject": True}
            if offset in st["got"]:
                # Duplicate of an already-applied chunk: the RPC layer's
                # at-least-once retry resent a chunk whose ack was lost.
                # Ack idempotently — aborting here would destroy our own
                # push.
                return {"ok": True}
            st["buf"].write_at(offset, chunk)
            st["got"].add(offset)
            st["bytes"] += len(chunk)
            st["ts"] = time.monotonic()
            if st["bytes"] < total:
                return {"ok": True}
            with self._push_lock:
                self._push_partial.pop(oid, None)
            st["buf"].close()
        try:
            self.store.seal(oid)
        except Exception:
            try:
                self.store.delete(oid)
            except Exception:
                pass
            return {"reject": True}
        try:
            get_client(self.conductor_address).call(
                "add_object_location", oid=oid, node_id=self.node_id)
        except Exception:
            pass  # location registration is best-effort; pulls re-register
        return {"done": True}

    # -- compiled-graph channel forwarder (dag/channel.py) ---------------

    def rpc_channel_write(self, chan_id: bytes, seq: int, data,
                          flags: int = 0,
                          timeout: Optional[float] = None) -> dict:
        """Forward a cross-host compiled-graph slot write into the local
        shm ring (the channel's reader lives on this node). Blocking is
        fine here: classic frames dispatch on the executor pool, and the
        ring itself provides the backpressure (a full ring means the
        consumer is max_in_flight behind)."""
        from ray_tpu.dag.channel import ChannelError, ShmChannelWriter
        with self._chan_lock:
            w = self._chan_writers.get(chan_id)
        if w is None:
            try:
                w = ShmChannelWriter(self.store, chan_id)
            except ChannelError as e:
                return {"ok": False, "error": str(e)}
            with self._chan_lock:
                w = self._chan_writers.setdefault(chan_id, w)
        try:
            w.write(seq, data, int(flags), timeout=timeout)
        except ChannelError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True}

    def rpc_channel_close(self, chan_id: bytes) -> dict:
        with self._chan_lock:
            w = self._chan_writers.pop(chan_id, None)
        if w is not None:
            try:
                w.close()
            except Exception:
                pass
        return {"ok": True}

    def rpc_delete_object(self, oid: bytes) -> None:
        try:
            self.store.delete(oid)
        except Exception:
            pass
        self._drop_spilled(oid)

    def rpc_delete_objects(self, oids: List[bytes]) -> None:
        """Batched GC deletes (the conductor's free loop coalesces — a
        small-object churn otherwise turns into thousands of serial
        single-delete RPCs that monopolize the store's event loop)."""
        for oid in oids:
            try:
                self.store.delete(oid)
            except Exception:
                pass
            self._drop_spilled(oid)

    def rpc_store_stats(self) -> dict:
        return self.store.stats()

    # ------------------------------------------------------------------
    # jobs (parity: dashboard/modules/job/job_manager.py:507 — the head
    # node runs the entrypoint as a supervised subprocess; records live in
    # the conductor KV so they survive failover)
    # ------------------------------------------------------------------
    def _job_update(self, submission_id: str, **fields) -> None:
        import pickle
        cli = get_client(self.conductor_address)
        try:
            blob = cli.call("kv_get", ns="_jobs", key=submission_id.encode())
            rec = pickle.loads(blob) if blob else {"submission_id":
                                                   submission_id}
            rec.update(fields)
            cli.call("kv_put", ns="_jobs", key=submission_id.encode(),
                     value=pickle.dumps(rec))
        except Exception:
            pass

    def rpc_start_job(self, submission_id: str, entrypoint: str,
                      runtime_env: Optional[dict],
                      conductor_address: str) -> dict:
        # Idempotent by submission id: the client retries dispatch
        # at-least-once (a lost ACK must not double-start the entrypoint).
        with self._lock:
            existing = self._jobs.get(submission_id)
        if existing is not None:
            return {"ok": True, "log_path": existing["log"]}
        log_path = os.path.join(self.session_dir,
                                f"job-{submission_id}.log")
        env = dict(os.environ)
        env.update(self._env_vars)
        env["RAY_TPU_ADDRESS"] = conductor_address
        env.setdefault("JAX_PLATFORMS", "cpu")
        if runtime_env and runtime_env.get("env_vars"):
            env.update({str(k): str(v)
                        for k, v in runtime_env["env_vars"].items()})
        if runtime_env and runtime_env.get("py_modules"):
            from ray_tpu.runtime_env import unpack_py_modules
            extra = unpack_py_modules(
                runtime_env["py_modules"],
                os.path.join(self.session_dir, "py_modules"))
            if extra:
                prev = env.get("PYTHONPATH", "")
                env["PYTHONPATH"] = (extra + os.pathsep + prev) if prev \
                    else extra
        cwd = (runtime_env or {}).get("working_dir") or None
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                ["/bin/sh", "-c", entrypoint], env=env, cwd=cwd,
                stdout=logf, stderr=subprocess.STDOUT)
        except OSError as e:
            self._job_update(submission_id, status="FAILED",
                             message=str(e), end_time=time.time())
            return {"ok": False}
        finally:
            logf.close()  # the child holds its own dup of the fd
        with self._lock:
            self._jobs[submission_id] = {"proc": proc, "log": log_path,
                                         "stopped": False}
        self._job_update(submission_id, status="RUNNING",
                         start_time=time.time())
        threading.Thread(target=self._job_waiter, daemon=True,
                         args=(submission_id, proc),
                         name=f"job-{submission_id[:12]}").start()
        return {"ok": True, "log_path": log_path}

    def _job_waiter(self, submission_id: str, proc: subprocess.Popen) -> None:
        code = proc.wait()
        with self._lock:
            stopped = self._jobs.get(submission_id, {}).get("stopped")
        if stopped:
            status, msg = "STOPPED", "stopped by user"
        elif code == 0:
            status, msg = "SUCCEEDED", ""
        else:
            status, msg = "FAILED", f"entrypoint exited with code {code}"
        self._job_update(submission_id, status=status, message=msg,
                         end_time=time.time())
        try:
            get_client(self.conductor_address).call(
                "report_event",
                severity="INFO" if status == "SUCCEEDED" else "WARNING",
                source=f"daemon-{self.node_id.hex()[:8]}",
                event_type=f"JOB_{status}",
                message=f"job {submission_id} {status.lower()}"
                        + (f": {msg}" if msg else ""),
                metadata={"submission_id": submission_id})
        except Exception:
            pass

    def rpc_stop_job(self, submission_id: str) -> bool:
        with self._lock:
            job = self._jobs.get(submission_id)
            if job is None:
                return False
            job["stopped"] = True
        try:
            job["proc"].terminate()
        except OSError:
            pass
        return True

    def rpc_job_log(self, submission_id: str, offset: int = 0,
                    max_bytes: int = 1 << 20) -> dict:
        with self._lock:
            job = self._jobs.get(submission_id)
        path = job["log"] if job else os.path.join(
            self.session_dir, f"job-{submission_id}.log")
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(max_bytes)
        except OSError:
            data = b""
        return {"data": data, "next_offset": offset + len(data)}

    # ------------------------------------------------------------------
    # worker-log tailer (parity: _private/log_monitor.py:104 — publish new
    # worker stdout/stderr lines to the conductor's log channel)
    # ------------------------------------------------------------------
    def _log_monitor_loop(self) -> None:
        import glob
        offsets: Dict[str, int] = {}
        cli = get_client(self.conductor_address)
        while not self._stopped:
            time.sleep(0.25)
            batch: List[dict] = []
            commits: List[tuple] = []   # (path, new_offset) — applied only
            # after a successful publish, so failures re-read not drop
            for path in glob.glob(os.path.join(self.session_dir,
                                               "worker-*.out")):
                try:
                    size = os.path.getsize(path)
                    off = offsets.get(path, 0)
                    if size <= off:
                        continue
                    with open(path, "rb") as f:
                        f.seek(off)
                        chunk = f.read(min(size - off, 1 << 20))
                except OSError:
                    continue  # this file vanished; others still ship
                # ship whole lines only; carry partials forward
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    continue
                pid = os.path.basename(path)[len("worker-"):-len(".out")]
                for line in chunk[:cut].decode(errors="replace").splitlines():
                    batch.append({"node": self.node_id.hex()[:8],
                                  "worker": pid, "line": line})
                commits.append((path, off + cut + 1))
            if not batch:
                continue
            try:
                for i in range(0, len(batch), 1000):
                    cli.call("push_logs", lines=batch[i:i + 1000])
            except Exception:
                continue  # offsets not advanced: lines re-read next tick
            for path, new_off in commits:
                offsets[path] = new_off

    def rpc_ping(self) -> str:
        return "pong"

    def rpc_debug_state(self) -> dict:
        """Structured debug-state dump (raylet debug_state.txt role: the
        node manager's table sizes, pools, budgets — machine-readable)."""
        with self._lock:
            state = {
                "role": "daemon",
                "node_id": self.node_id.hex(),
                "pid": os.getpid(),
                "is_head": self.is_head,
                "resources_total": dict(self.total_resources),
                "resources_available": dict(self._avail),
                "workers": len(self._workers),
                "worker_pids": sorted(
                    w.pid for w in self._workers.values())[:128],
                "idle_workers": {k: len(q)
                                 for k, q in self._idle.items() if q},
                "leases": len(self._leases),
                "bundles": len(self._bundles),
                "pending_demand": len(self._pending_demand),
                "pending_death_reports": len(self._pending_death_reports),
                "prestarting": self._prestarting,
                "jobs": len(self._jobs),
            }
        with self._push_lock:
            state["push_partial"] = len(self._push_partial)
        with self._serve_lock:
            state["serve_views"] = len(self._serve_views)
            state["serving_chunks"] = self._serving_chunks
            state["served_chunks"] = self._served_chunks
            state["remote_pins"] = len(self._remote_pins)
        # Tiering lines (raylet debug_state.txt "Spilled/Restored/Evicted"
        # rows): coordinated registry + the store's own counters.
        with self._spill_lock:
            state["spilled_objects"] = len(self._spilled)
            state["spilled_bytes"] = sum(e[1]
                                         for e in self._spilled.values())
            state["num_spilled"] = self._num_spilled
            state["num_restored_serves"] = self._num_restored_serves
        try:
            st = self.store.stats()
            state["store"] = st
            state["Spilled"] = st.get("spills", 0)
            state["Restored"] = st.get("restores", 0)
            state["Evicted"] = st.get("evictions", 0)
        except Exception:
            pass
        return state

    def rpc_profile_worker(self, pid: int, duration_s: float = 1.0,
                           interval_s: float = 0.01) -> Optional[str]:
        """Profile the worker with this OS pid (None when the pid is not
        one of ours). Parity: the dashboard agent's py-spy trigger,
        reporter/profile_manager.py — here over the worker's RPC server."""
        with self._lock:
            target = next((w for w in self._workers.values()
                           if w.pid == pid and w.address), None)
        if target is None:
            return None
        return get_client(target.address).call(
            "profile", duration_s=duration_s, interval_s=interval_s,
            _timeout=float(duration_s) + 30.0)

    # ------------------------------------------------------------------
    def stop(self) -> None:
        self._stopped = True
        if self._oom_monitor is not None:
            self._oom_monitor.stop()
        with self._bcast_plane_lock:
            plane, self._bcast_plane = self._bcast_plane, None
        if plane is not None:
            plane.stop()
        with self._lock:
            pool, self._actor_start_pool = self._actor_start_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            try:
                w.proc.kill()
            except OSError:
                pass
        with self._zygote_lock:
            z, self._zygote_proc = self._zygote_proc, False
        if z not in (None, False):
            try:
                z.kill()
            except OSError:
                pass
        self.server.stop()
        try:
            self.store.close()
            # SIGTERM first: lets the store unlink its segments (its
            # cleanup_all path); escalate only if it lingers.
            self.store_proc.terminate()
            try:
                self.store_proc.wait(timeout=2.0)
            except Exception:
                self.store_proc.kill()
                self.store_proc.wait()  # reap: no zombie for driver life
        except Exception:
            pass
        if self._owns_session_dir:
            import shutil
            shutil.rmtree(self.session_dir, ignore_errors=True)
