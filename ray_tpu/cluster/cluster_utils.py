"""In-process multi-node test cluster.

Role parity: python/ray/cluster_utils.py:99 (Cluster, add_node:165,
remove_node:238) — the reference's standard way to test distributed
behavior (spillback, node death, transfer) without real machines: one
conductor plus N node daemons in this process, workers as real
subprocesses, each node with its own shm object store.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.cluster.conductor import Conductor
from ray_tpu.cluster.node_daemon import NodeDaemon
from ray_tpu.cluster.protocol import get_client


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 health_timeout_s: float = 3.0,
                 host: str = "127.0.0.1"):
        self.conductor = Conductor(host=host,
                                   health_timeout_s=health_timeout_s)
        self.address = self.conductor.address
        self.nodes: List[NodeDaemon] = []
        if initialize_head:
            # The auto-created head inherits the CLUSTER host unless the
            # caller overrides it (a conductor on a LAN IP with its head
            # node quietly on 127.0.0.1 would be unreachable remotely).
            self.add_node(is_head=True,
                          **{"host": host, **(head_node_args or {})})

    def add_node(self, num_cpus: float = 4.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_bytes: int = 256 << 20,
                 is_head: bool = False,
                 tpu_slice: Optional[dict] = None,
                 host: str = "127.0.0.1") -> NodeDaemon:
        """``tpu_slice`` injects fake slice membership (slice_id,
        accelerator_type, generation, worker_id, num_hosts) — the test
        analog of a real TPU host's env-derived topology.detect_slice()."""
        total = {"CPU": float(num_cpus)}
        if num_tpus:
            total["TPU"] = float(num_tpus)
        total.update(resources or {})
        node = NodeDaemon(self.address, resources=total, host=host,
                          object_store_bytes=object_store_bytes,
                          is_head=is_head, tpu_slice=tpu_slice)
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeDaemon, graceful: bool = False) -> None:
        """Kill a node (workers included). graceful=True tells the conductor
        first; False simulates a crash (health check finds out)."""
        if graceful:
            try:
                get_client(self.address).call("drain_node",
                                              node_id=node.node_id)
            except Exception:
                pass
        node.stop()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, count: int, timeout: float = 10.0) -> None:
        import time
        deadline = time.monotonic() + timeout
        cli = get_client(self.address)
        while time.monotonic() < deadline:
            alive = [n for n in cli.call("get_nodes") if n["alive"]]
            if len(alive) >= count:
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster never reached {count} nodes")

    def shutdown(self) -> None:
        for node in list(self.nodes):
            node.stop()
        self.nodes.clear()
        self.conductor.stop()
