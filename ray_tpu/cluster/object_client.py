"""Client for the shmstore daemon (native/shmstore/shmstore.cc).

Zero-copy reads: the daemon backs each object with a POSIX shm segment; the
client mmaps /dev/shm/<prefix><oid> directly and hands out memoryviews, so a
100 GiB numpy array is never copied through a socket (parity with the
reference's plasma get path, reference core_worker.cc:1307 -> plasma mmap).

Write path: puts go through pwrite() into the shm file between CREATE and
SEAL (plasma's create->write->seal, reference plasma/store.h:55) — on tmpfs
a syscall write into fresh pages is ~2.5x faster than a first-touch mmap
store (no per-page zero-fill fault storm), and into daemon-recycled pages
it is a straight memcpy.

Ref lifetime: `get_pinned` holds the store-side reference until the LAST
user view of the mapping is garbage collected (weakref.finalize on the
mmap), which is what makes the daemon's page recycling safe — a numpy array
backed by the mapping pins the object exactly like a plasma buffer pins its
arena slice. Releases are queued and piggybacked on the next store call
(finalizers may fire at arbitrary GC points where taking the socket lock
could deadlock or interleave frames).

Thread-safe: one lock around the request/response socket; data-plane reads
go straight to shared memory without holding it.
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import subprocess
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

OP_CREATE, OP_SEAL, OP_GET, OP_RELEASE, OP_DELETE, OP_CONTAINS, OP_STATS, \
    OP_LIST, OP_GET_COPY, OP_PUT_INLINE, OP_GET_COPY_BATCH, \
    OP_CONTAINS_BATCH, OP_SPILL_CANDIDATES, OP_EVICT = range(1, 15)
ST_OK, ST_NOT_FOUND, ST_EXISTS, ST_OOM, ST_TIMEOUT, ST_ERR, ST_NOT_SEALED, \
    ST_BUSY = range(8)


def _default_inline_max() -> int:
    """Inline-get size cap = the system-wide small-object threshold
    (config max_inline_object_bytes); the daemon has no server-side cap —
    the client's max_bytes alone decides inline vs zero-copy."""
    from ray_tpu import config
    return int(config.get("max_inline_object_bytes"))

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
SHMSTORED = os.path.join(_NATIVE_DIR, "shmstored")


class ObjectStoreError(Exception):
    pass


class ObjectStoreFullError(ObjectStoreError):
    pass


def ensure_built() -> str:
    """Build the daemon from source if the binary is missing."""
    if not os.path.exists(SHMSTORED):
        src_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "native")
        subprocess.run(["make", "-C", src_dir], check=True,
                       capture_output=True)
    return SHMSTORED


def start_store(sock_path: str, capacity: int, prefix: str,
                spill_dir: Optional[str] = None) -> subprocess.Popen:
    """Launch shmstored; waits for its READY line."""
    ensure_built()
    args = [SHMSTORED, sock_path, str(capacity), prefix]
    if spill_dir:
        args.append(spill_dir)
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    if not line.startswith("READY"):
        proc.kill()
        raise ObjectStoreError(f"shmstored failed to start: {line!r}")
    return proc


class _MapCache:
    """Per-process cache of writable mappings over recycled shm segments.

    The daemon recycles retired segments (same inode comes back for the
    next same-sized create, via rename). A mapping whose page tables are
    already populated turns a 100MB fill into a plain memcpy (~2x over
    pwrite, ~6x over a fresh-page mmap store). Identity is (st_dev,
    st_ino); each entry KEEPS ITS FD OPEN, which pins the inode so the
    inode number cannot be recycled for an unrelated file while cached —
    that's what makes the (dev, ino) check sound. Bounded by entries and
    bytes; LRU."""

    _MAX_ENTRIES = 8
    _MAX_BYTES = 512 << 20
    _MIN_SIZE = 1 << 20  # small objects gain nothing from mapping reuse

    def __init__(self):
        self._entries: "Dict[Tuple[int, int], Tuple[int, mmap.mmap, int]]" \
            = {}  # (dev, ino) -> (kept_fd, mmap, size)
        self._order: "deque[Tuple[int, int]]" = deque()
        self._bytes = 0
        self._last_sweep = 0.0
        self._lock = threading.Lock()

    def lookup(self, fd: int, size: int) -> Optional[mmap.mmap]:
        if size < self._MIN_SIZE:
            return None
        st = os.fstat(fd)
        key = (st.st_dev, st.st_ino)
        with self._lock:
            # Sweep from the read path too (rate-limited): a process that
            # stops WRITING must still drop pins on segments the store
            # already unlinked, or its cached fd+mmap keep tmpfs pages
            # resident that the store's accounting says are free.
            now = time.monotonic()
            if now - self._last_sweep > 0.5:
                self._last_sweep = now
                self._sweep_unlinked_locked()
            ent = self._entries.get(key)
            if ent is not None and ent[2] == size:
                self._order.remove(key)
                self._order.append(key)
                return ent[1]
        return None

    def _sweep_unlinked_locked(self) -> None:
        """Drop entries whose inode the store already unlinked (evicted
        pool segment): st_nlink==0 means OUR fd+mmap are the only thing
        keeping those tmpfs pages resident — memory the store believes it
        freed. Caller holds the lock; a handful of fstats."""
        for key in list(self._entries):
            kfd, _kmm, ksize = self._entries[key]
            try:
                alive = os.fstat(kfd).st_nlink > 0
            except OSError:
                alive = False
            if not alive:
                self._order.remove(key)
                kfd, _kmm, ksize = self._entries.pop(key)
                self._bytes -= ksize
                os.close(kfd)  # mmap ref dropped; GC unmaps when unused

    def sweep(self) -> None:
        """Periodic-timer entry point (ShmClient's 1Hz drain loop): drop
        pins on store-unlinked segments even when this process has gone
        idle on the put path."""
        with self._lock:
            self._sweep_unlinked_locked()

    def insert(self, fd: int, size: int) -> None:
        """Map (unfaulted; faults resolve on first cached write) and keep a
        dup'd fd so the inode stays pinned."""
        if size < self._MIN_SIZE or size > self._MAX_BYTES:
            return
        st = os.fstat(fd)
        key = (st.st_dev, st.st_ino)
        with self._lock:
            self._sweep_unlinked_locked()
            if key in self._entries:
                return
            keep = os.dup(fd)
            try:
                mm = mmap.mmap(keep, size)
            except (OSError, ValueError):
                os.close(keep)
                return
            self._entries[key] = (keep, mm, size)
            self._order.append(key)
            self._bytes += size
            while (len(self._entries) > self._MAX_ENTRIES or
                   self._bytes > self._MAX_BYTES):
                old = self._order.popleft()
                kfd, kmm, ksize = self._entries.pop(old)
                self._bytes -= ksize
                # Do NOT kmm.close(): a concurrent ShmWriter that got this
                # mapping from lookup() may be mid-copy, and closing under
                # it turns its next slice-assign into a hard error. Drop
                # the reference — GC unmaps once the last writer lets go.
                del kmm
                os.close(kfd)


_map_cache = _MapCache()


class ShmWriter:
    """Filler for a CREATED object (close(), then seal()).

    Fast paths, in order: a cached mapping of a recycled segment (pure
    memcpy — page tables already populated), else pwrite() (skips the
    per-4KB fault+zero-fill storm a fresh-page mmap store pays, ~2.5x on a
    100MB put)."""

    _WRITE_CHUNK = 32 << 20  # cap single pwrite size (signed-int syscalls)

    def __init__(self, fd: int, size: int):
        self._fd = fd
        self.size = size
        self._mm = _map_cache.lookup(fd, size) if fd >= 0 else None

    def write_at(self, offset: int, data) -> int:
        m = memoryview(data)
        if m.format != "B":
            m = m.cast("B")
        if m.nbytes and not m.contiguous:
            m = memoryview(bytes(m))
        n = m.nbytes
        if self._mm is not None:
            self._mm[offset:offset + n] = m
            return n
        off = 0
        while off < n:
            off += os.pwrite(self._fd, m[off:off + self._WRITE_CHUNK],
                             offset + off)
        return n

    def close(self) -> None:
        if self._fd >= 0:
            if self._mm is None:
                # Populate the cache so the NEXT same-sized recycle of this
                # segment writes through the mapping.
                _map_cache.insert(self._fd, self.size)
            self._mm = None
            os.close(self._fd)
            self._fd = -1

    def __del__(self):
        self.close()


class ShmClient:
    """Connection to one node's shmstored."""

    def __init__(self, sock_path: str, prefix: str):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(sock_path)
        self._prefix = prefix
        self._lock = threading.Lock()
        self._maps: Dict[bytes, Tuple[mmap.mmap, int]] = {}
        # Releases queued by mmap finalizers (get_pinned): flushed on the
        # next store call under the socket lock. A finalizer must never
        # touch the socket itself — it can fire mid-_call on this very
        # thread (GC during allocation) and would deadlock or corrupt the
        # frame stream. A background drain covers the idle case: a process
        # that stops calling the store must still drop its pins, or the
        # daemon can never delete/evict those objects (deferred-delete +
        # recycling both key off refcount 0).
        self._deferred_releases: "deque[bytes]" = deque()
        self._closed = False
        threading.Thread(target=self._release_drain_loop, daemon=True,
                         name="shm-release-drain").start()

    def _queue_release(self, oid: bytes) -> None:
        # Append ONLY — a finalizer may fire inside any lock/Event
        # critical section on this very thread; deque.append is the one
        # operation that is safe everywhere.
        self._deferred_releases.append(oid)

    def _release_drain_loop(self) -> None:
        # 1Hz poll (not event-driven: finalizers can't safely signal an
        # Event). Cheap — one wakeup/sec/client, and _call() drains
        # eagerly in active processes anyway.
        while not self._closed:
            time.sleep(1.0)
            if self._closed:
                return
            _map_cache.sweep()
            if not self._deferred_releases:
                continue
            try:
                self._drain_releases()
            except Exception:
                return  # socket gone; the daemon reaps on disconnect

    def _drain_releases(self) -> None:
        # _lock IS the wire lock: it exists to serialize request/reply
        # framing on this store socket, so socket I/O under it is the
        # design, not a hazard (local unix socket, store replies are µs).
        with self._lock:
            while self._deferred_releases:
                oid = self._deferred_releases.popleft()
                self._sock.sendall(struct.pack(     # rtcheck: allow-blocking(wire lock: serializes framing on the local store socket)
                    "<IB16s", 17, OP_RELEASE, oid))
                self._read_frame()

    # --- framing ---------------------------------------------------------
    def _call(self, payload: bytes) -> bytes:
        with self._lock:
            while self._deferred_releases:
                oid = self._deferred_releases.popleft()
                self._sock.sendall(struct.pack(     # rtcheck: allow-blocking(wire lock: serializes framing on the local store socket)
                    "<IB16s", 17, OP_RELEASE, oid))
                self._read_frame()
            self._sock.sendall(struct.pack("<I", len(payload)) + payload)  # rtcheck: allow-blocking(wire lock: serializes framing on the local store socket)
            return self._read_frame()

    def _read_frame(self) -> bytes:
        hdr = self._recv_exact(4)
        (length,) = struct.unpack("<I", hdr)
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ObjectStoreError("store connection closed")
            buf += chunk
        return buf

    # --- object ops ------------------------------------------------------
    def _shm_path(self, oid: bytes) -> str:
        return f"/dev/shm/{self._prefix}{oid.hex()}"

    def _create_rpc(self, oid: bytes, size: int) -> None:
        deadline = time.monotonic() + 5.0
        while True:
            resp = self._call(struct.pack("<B16sQ", OP_CREATE, oid, size))
            st = resp[0]
            if st == ST_BUSY:
                # Previous incarnation of this id is pending_delete with
                # live reader pins; the name frees once they drain. Retry
                # briefly rather than mis-reporting "already exists".
                if time.monotonic() < deadline:
                    time.sleep(0.002)
                    continue
                raise ObjectStoreError(
                    f"object {oid.hex()} stuck pending delete (pinned)")
            if st == ST_OOM:
                raise ObjectStoreFullError(
                    f"object of {size} bytes doesn't fit")
            if st == ST_EXISTS:
                raise ObjectStoreError(f"object {oid.hex()} already exists")
            if st != ST_OK:
                raise ObjectStoreError(f"create failed: status {st}")
            return

    def create(self, oid: bytes, size: int) -> memoryview:
        """Reserve an object and return a writable view; seal() when done."""
        self._create_rpc(oid, size)
        fd = os.open(self._shm_path(oid), os.O_RDWR)
        try:
            mm = mmap.mmap(fd, size) if size else mmap.mmap(-1, 1)
        finally:
            os.close(fd)
        return memoryview(mm)[:size] if size else memoryview(b"")

    def create_writer(self, oid: bytes, size: int) -> "ShmWriter":
        """Reserve an object for pwrite()-based filling (the fast put path:
        no page-fault storm on fresh tmpfs pages, straight memcpy into
        daemon-recycled ones). seal() when done."""
        self._create_rpc(oid, size)
        fd = os.open(self._shm_path(oid), os.O_RDWR) if size else -1
        return ShmWriter(fd, size)

    def seal(self, oid: bytes) -> None:
        resp = self._call(struct.pack("<B16s", OP_SEAL, oid))
        if resp[0] != ST_OK:
            raise ObjectStoreError(f"seal failed: status {resp[0]}")

    def put(self, oid: bytes, data) -> None:
        data = memoryview(data)
        w = self.create_writer(oid, data.nbytes)
        try:
            w.write_at(0, data)
        finally:
            w.close()
        self.seal(oid)

    def get(self, oid: bytes, timeout: Optional[float] = None
            ) -> Optional[memoryview]:
        """Blocking get -> zero-copy readonly view; None when the object is
        not available (timeout, not created yet, or writer has not sealed).
        Pair with an explicit release() once done reading (and do not
        retain views past it — use get_pinned for that)."""
        got = self._get_map(oid, timeout)
        if got is None:
            return None
        mm, size = got
        if mm is None:
            return memoryview(b"")
        self._maps[oid] = (mm, size)
        return memoryview(mm)

    def get_pinned(self, oid: bytes, timeout: Optional[float] = None
                   ) -> Optional[memoryview]:
        """Zero-copy get whose store reference lives exactly as long as the
        mapping: released (via the deferred queue) when the LAST view —
        e.g. a numpy array deserialized over it — is garbage collected. No
        explicit release; this is what makes daemon page recycling safe."""
        got = self._get_map(oid, timeout)
        if got is None:
            return None
        mm, _size = got
        if mm is None:
            # Zero-byte objects have no mapping to pin; drop the ref now.
            self._queue_release(bytes(oid))
            return memoryview(b"")
        weakref.finalize(mm, self._queue_release, bytes(oid))
        return memoryview(mm)

    def _get_map(self, oid: bytes, timeout: Optional[float]):
        """Shared get machinery -> None (unavailable) | (mmap|None, size);
        the store ref is held — the caller decides release discipline."""
        timeout_ms = -1 if timeout is None else int(timeout * 1000)
        resp = self._call(struct.pack("<B16sq", OP_GET, oid, timeout_ms))
        st = resp[0]
        if st in (ST_TIMEOUT, ST_NOT_FOUND, ST_NOT_SEALED):
            # NOT_SEALED: a writer is mid-create; readers retry like not-yet-
            # created (sealing is the visibility barrier, plasma semantics).
            return None
        if st != ST_OK:
            raise ObjectStoreError(f"get failed: status {st}")
        (size,) = struct.unpack("<Q", resp[1:9])
        if size == 0:
            return (None, 0)
        fd = os.open(self._shm_path(oid), os.O_RDONLY)
        try:
            return (mmap.mmap(fd, size, prot=mmap.PROT_READ), size)
        finally:
            os.close(fd)

    def put_inline(self, oid: bytes, data) -> bool:
        """Small-object put: create+copy+seal in ONE store round trip (the
        write path analog of get_inline). False when the object already
        exists (same no-op semantics as the create path)."""
        m = memoryview(data)
        if m.format != "B":
            m = m.cast("B")
        resp = self._call(struct.pack("<B16s", OP_PUT_INLINE, oid) +
                          bytes(m))
        st = resp[0]
        if st == ST_EXISTS:
            return False
        if st == ST_OOM:
            raise ObjectStoreFullError(
                f"object of {m.nbytes} bytes doesn't fit")
        if st != ST_OK:
            raise ObjectStoreError(f"put_inline failed: status {st}")
        return True

    def put_inline_batch(self, items) -> int:
        """Pipelined small-object puts: every OP_PUT_INLINE frame hits the
        wire before the first reply is read (the daemon serves one
        connection's requests serially and in order, so replies match
        request order). One send/recv burst per batch instead of a store
        round trip per object — this is the lazy sealer's backstop write
        load, stolen from the task ping-pong on small hosts.

        ``items``: iterable of (oid16, bytes-like). Per-object failures
        (exists/OOM) are tolerated — returns the count actually written.
        """
        frames = []
        for oid, data in items:
            m = memoryview(data)
            if m.format != "B":
                m = m.cast("B")
            payload = struct.pack("<B16s", OP_PUT_INLINE, oid) + bytes(m)
            frames.append(struct.pack("<I", len(payload)) + payload)
        if not frames:
            return 0
        wrote = 0
        with self._lock:
            while self._deferred_releases:
                oid = self._deferred_releases.popleft()
                self._sock.sendall(struct.pack("<IB16s", 17, OP_RELEASE, oid))  # rtcheck: allow-blocking(wire lock: serializes framing on the local store socket)
                self._read_frame()
            self._sock.sendall(b"".join(frames))  # rtcheck: allow-blocking(wire lock: serializes framing on the local store socket)
            for _ in frames:
                if self._read_frame()[0] == ST_OK:
                    wrote += 1
        return wrote

    # Oids per OP_GET_COPY_BATCH round trip: bounds the daemon's reply
    # buffer (~100MB worst case at the default 100KB inline cap — raise
    # max_inline_object_bytes past ~4MB and this needs revisiting) and
    # keeps the reply length far from u32 framing limits.
    _GET_BATCH = 1024

    def get_inline_batch(self, oids: List[bytes],
                         max_bytes: Optional[int] = None
                         ) -> List[Optional[bytes]]:
        """Inline-get MANY objects in few round trips; None per miss
        (absent / unsealed / larger than max_bytes — callers fall back to
        the zero-copy path for those). max_bytes defaults to the config's
        max_inline_object_bytes."""
        if max_bytes is None:
            max_bytes = _default_inline_max()
        out: List[Optional[bytes]] = []
        for start in range(0, len(oids), self._GET_BATCH):
            chunk = oids[start:start + self._GET_BATCH]
            payload = struct.pack("<B16sIQ", OP_GET_COPY_BATCH, b"\0" * 16,
                                  len(chunk), max_bytes) + b"".join(chunk)
            resp = self._call(payload)
            if resp[0] != ST_OK:
                raise ObjectStoreError(
                    f"get_inline_batch failed: status {resp[0]}")
            pos = 1
            for _ in chunk:
                st = resp[pos]
                (size,) = struct.unpack_from("<Q", resp, pos + 1)
                pos += 9
                if st == ST_OK:
                    out.append(resp[pos:pos + size])
                    pos += size
                else:
                    out.append(None)
        return out

    def get_inline(self, oid: bytes,
                   max_bytes: Optional[int] = None) -> Optional[bytes]:
        """Small-object fast path (OP_GET_COPY): the sealed payload comes
        back INLINE in one round trip — no refcount, no mmap, no release.
        Returns None when the object is missing, unsealed, or larger than
        max_bytes (callers fall back to the zero-copy get/release path).
        max_bytes defaults to the config's max_inline_object_bytes.
        """
        if max_bytes is None:
            max_bytes = _default_inline_max()
        resp = self._call(struct.pack("<B16sQ", OP_GET_COPY, oid, max_bytes))
        st = resp[0]
        if st != ST_OK:
            return None
        (size,) = struct.unpack("<Q", resp[1:9])
        return resp[9:9 + size]

    def release(self, oid: bytes) -> None:
        mm = self._maps.pop(oid, None)
        self._call(struct.pack("<B16s", OP_RELEASE, oid))
        # the mmap view may still be referenced by user numpy arrays; let GC
        # close it (mmap keeps the pages alive independently of the store)

    def delete(self, oid: bytes) -> None:
        self._call(struct.pack("<B16s", OP_DELETE, oid))

    def contains(self, oid: bytes) -> bool:
        resp = self._call(struct.pack("<B16s", OP_CONTAINS, oid))
        return resp[0] == ST_OK

    def contains_batch(self, oids: List[bytes]) -> List[bool]:
        """Existence of MANY objects in few round trips — same sealed-and-
        visible predicate as contains(). Turns a wait() over 1k refs into
        one store round trip instead of 1k."""
        out: List[bool] = []
        for start in range(0, len(oids), self._GET_BATCH):
            chunk = oids[start:start + self._GET_BATCH]
            payload = struct.pack("<BI", OP_CONTAINS_BATCH,
                                  len(chunk)) + b"".join(chunk)
            resp = self._call(payload)
            if resp[0] != ST_OK:
                raise ObjectStoreError(
                    f"contains_batch failed: status {resp[0]}")
            out.extend(b != 0 for b in resp[1:1 + len(chunk)])
        return out

    def spill_candidates(self, max_bytes: int = 0
                         ) -> List[Tuple[bytes, int]]:
        """Cold unreferenced SEALED primaries worth spilling, coldest
        first, totalling at least ``max_bytes`` (0 = every candidate).
        Read-only: the spill coordinator copies the bytes out through its
        backend, then calls evict() per object."""
        resp = self._call(struct.pack("<BQ", OP_SPILL_CANDIDATES, max_bytes))
        if resp[0] != ST_OK:
            raise ObjectStoreError(
                f"spill_candidates failed: status {resp[0]}")
        body = resp[1:]
        out: List[Tuple[bytes, int]] = []
        for i in range(0, len(body), 24):
            oid = bytes(body[i:i + 16])
            (size,) = struct.unpack_from("<Q", body, i + 16)
            out.append((oid, size))
        return out

    def evict(self, oid: bytes) -> Optional[int]:
        """Evict-with-report: drop this object's store copy NOW (the caller
        holds a durable copy elsewhere). Returns bytes freed, or None when
        the store refused — pinned by a reader (ST_BUSY), unsealed, or
        already gone; refusal means the copy stays and the caller simply
        keeps both."""
        resp = self._call(struct.pack("<B16s", OP_EVICT, oid))
        if resp[0] != ST_OK:
            return None
        (freed,) = struct.unpack("<Q", resp[1:9])
        return freed

    def stats(self) -> dict:
        import json
        resp = self._call(struct.pack("<B", OP_STATS))
        return json.loads(resp[1:].decode())

    def list_objects(self) -> List[bytes]:
        resp = self._call(struct.pack("<B", OP_LIST))
        body = resp[1:]
        return [bytes(body[i:i + 16]) for i in range(0, len(body), 16)]

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
