"""Client for the shmstore daemon (native/shmstore/shmstore.cc).

Zero-copy reads: the daemon backs each object with a POSIX shm segment; the
client mmaps /dev/shm/<prefix><oid> directly and hands out memoryviews, so a
100 GiB numpy array is never copied through a socket (parity with the
reference's plasma get path, reference core_worker.cc:1307 -> plasma mmap).

Thread-safe: one lock around the request/response socket; data-plane reads
go straight to shared memory without holding it.
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

OP_CREATE, OP_SEAL, OP_GET, OP_RELEASE, OP_DELETE, OP_CONTAINS, OP_STATS, \
    OP_LIST, OP_GET_COPY = range(1, 10)
ST_OK, ST_NOT_FOUND, ST_EXISTS, ST_OOM, ST_TIMEOUT, ST_ERR, ST_NOT_SEALED = \
    range(7)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
SHMSTORED = os.path.join(_NATIVE_DIR, "shmstored")


class ObjectStoreError(Exception):
    pass


class ObjectStoreFullError(ObjectStoreError):
    pass


def ensure_built() -> str:
    """Build the daemon from source if the binary is missing."""
    if not os.path.exists(SHMSTORED):
        src_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "native")
        subprocess.run(["make", "-C", src_dir], check=True,
                       capture_output=True)
    return SHMSTORED


def start_store(sock_path: str, capacity: int, prefix: str,
                spill_dir: Optional[str] = None) -> subprocess.Popen:
    """Launch shmstored; waits for its READY line."""
    ensure_built()
    args = [SHMSTORED, sock_path, str(capacity), prefix]
    if spill_dir:
        args.append(spill_dir)
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    if not line.startswith("READY"):
        proc.kill()
        raise ObjectStoreError(f"shmstored failed to start: {line!r}")
    return proc


class ShmClient:
    """Connection to one node's shmstored."""

    def __init__(self, sock_path: str, prefix: str):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(sock_path)
        self._prefix = prefix
        self._lock = threading.Lock()
        self._maps: Dict[bytes, Tuple[mmap.mmap, int]] = {}

    # --- framing ---------------------------------------------------------
    def _call(self, payload: bytes) -> bytes:
        with self._lock:
            self._sock.sendall(struct.pack("<I", len(payload)) + payload)
            return self._read_frame()

    def _read_frame(self) -> bytes:
        hdr = self._recv_exact(4)
        (length,) = struct.unpack("<I", hdr)
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ObjectStoreError("store connection closed")
            buf += chunk
        return buf

    # --- object ops ------------------------------------------------------
    def _shm_path(self, oid: bytes) -> str:
        return f"/dev/shm/{self._prefix}{oid.hex()}"

    def create(self, oid: bytes, size: int) -> memoryview:
        """Reserve an object and return a writable view; seal() when done."""
        resp = self._call(struct.pack("<B16sQ", OP_CREATE, oid, size))
        st = resp[0]
        if st == ST_OOM:
            raise ObjectStoreFullError(f"object of {size} bytes doesn't fit")
        if st == ST_EXISTS:
            raise ObjectStoreError(f"object {oid.hex()} already exists")
        if st != ST_OK:
            raise ObjectStoreError(f"create failed: status {st}")
        fd = os.open(self._shm_path(oid), os.O_RDWR)
        try:
            mm = mmap.mmap(fd, size) if size else mmap.mmap(-1, 1)
        finally:
            os.close(fd)
        return memoryview(mm)[:size] if size else memoryview(b"")

    def seal(self, oid: bytes) -> None:
        resp = self._call(struct.pack("<B16s", OP_SEAL, oid))
        if resp[0] != ST_OK:
            raise ObjectStoreError(f"seal failed: status {resp[0]}")

    def put(self, oid: bytes, data) -> None:
        data = memoryview(data)
        buf = self.create(oid, data.nbytes)
        buf[:] = data.cast("B") if data.format != "B" else data
        self.seal(oid)

    def get(self, oid: bytes, timeout: Optional[float] = None
            ) -> Optional[memoryview]:
        """Blocking get -> zero-copy readonly view; None when the object is
        not available (timeout, not created yet, or writer has not sealed)."""
        timeout_ms = -1 if timeout is None else int(timeout * 1000)
        resp = self._call(struct.pack("<B16sq", OP_GET, oid, timeout_ms))
        st = resp[0]
        if st in (ST_TIMEOUT, ST_NOT_FOUND, ST_NOT_SEALED):
            # NOT_SEALED: a writer is mid-create; readers retry like not-yet-
            # created (sealing is the visibility barrier, plasma semantics).
            return None
        if st != ST_OK:
            raise ObjectStoreError(f"get failed: status {st}")
        (size,) = struct.unpack("<Q", resp[1:9])
        if size == 0:
            return memoryview(b"")
        fd = os.open(self._shm_path(oid), os.O_RDONLY)
        try:
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        self._maps[oid] = (mm, size)
        return memoryview(mm)

    def get_inline(self, oid: bytes,
                   max_bytes: int = 64 << 10) -> Optional[bytes]:
        """Small-object fast path (OP_GET_COPY): the sealed payload comes
        back INLINE in one round trip — no refcount, no mmap, no release.
        Returns None when the object is missing, unsealed, or larger than
        max_bytes (callers fall back to the zero-copy get/release path).
        """
        resp = self._call(struct.pack("<B16sQ", OP_GET_COPY, oid, max_bytes))
        st = resp[0]
        if st != ST_OK:
            return None
        (size,) = struct.unpack("<Q", resp[1:9])
        return resp[9:9 + size]

    def release(self, oid: bytes) -> None:
        mm = self._maps.pop(oid, None)
        self._call(struct.pack("<B16s", OP_RELEASE, oid))
        # the mmap view may still be referenced by user numpy arrays; let GC
        # close it (mmap keeps the pages alive independently of the store)

    def delete(self, oid: bytes) -> None:
        self._call(struct.pack("<B16s", OP_DELETE, oid))

    def contains(self, oid: bytes) -> bool:
        resp = self._call(struct.pack("<B16s", OP_CONTAINS, oid))
        return resp[0] == ST_OK

    def stats(self) -> dict:
        import json
        resp = self._call(struct.pack("<B", OP_STATS))
        return json.loads(resp[1:].decode())

    def list_objects(self) -> List[bytes]:
        resp = self._call(struct.pack("<B", OP_LIST))
        body = resp[1:]
        return [bytes(body[i:i + 16]) for i in range(0, len(body), 16)]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
