"""Worker process: executes tasks and hosts actors.

Role parity: the core worker's execution half — HandlePushTask
(core_worker.cc:2925) -> ExecuteTask (:2525) -> the Python trampoline
(_raylet.pyx:718 execute_task), plus the receiver-side scheduling queues
(transport/actor_scheduling_queue.h: per-caller sequence-number ordering,
out-of-order mode for max_concurrency>1, asyncio actors standing in for the
boost::fiber loop of fiber.h) and the per-worker main loop
(default_worker.py:258 / core_worker_process.cc:63 RunTaskExecutionLoop).

One worker process == one lease at a time (normal tasks execute serially)
or one dedicated actor. Workers are also full API clients: user code running
here can submit nested tasks/actors through the same ClusterRuntime.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from ray_tpu.cluster import fault_plane, object_client
from ray_tpu.cluster.object_plane import ObjectPlane
from ray_tpu.cluster.protocol import RpcServer, get_client
from ray_tpu.core import serialization, task_spec
from ray_tpu.core import refs as _refs_mod
from ray_tpu.core.exceptions import (GetTimeoutError, ObjectLostError,
                                     TaskError)
from ray_tpu.core.ids import ObjectID, TaskID, WorkerID, store_key
from ray_tpu.util import events as _events


class _LazySealer:
    """Deferred store seal of reply-carried (inline) returns.

    The push reply carries the serialized result; the caller is already
    unblocked, so the store write is pure backstop work — it is what makes
    the object visible to remote pulls, wait(), and lineage reconstruction
    (the reference keeps small direct-call returns owner-memory-only; we
    diverge by sealing lazily so the rest of the object plane needs no
    special inline-object protocol). Runs on one background thread; a
    short defer lets the ack win the race to the wire and lets a burst of
    task results coalesce."""

    _DEFER_S = 0.001

    def __init__(self, plane: ObjectPlane):
        self.plane = plane
        self._q = deque()
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lazy-seal")
        self._thread.start()

    def enqueue(self, jobs) -> None:
        """jobs: iterable of (ObjectID, serialized blob)."""
        with self._cv:
            self._q.extend(jobs)
            self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
                jobs = list(self._q)
                self._q.clear()
            time.sleep(self._DEFER_S)
            batch = []
            for oid, blob in jobs:
                try:
                    # Fault point: the reply->seal gap. A "crash" rule here
                    # kills the worker AFTER the caller cached the value
                    # but BEFORE any store copy exists — the window where
                    # remote consumers must get a lost verdict (probe miss
                    # on the pre-registered location) and recover via
                    # lineage instead of hanging.
                    fault_plane.fire("task.return.seal", oid=oid.hex())
                    batch.append((oid, blob))
                except Exception:
                    pass  # fault rule raised: skip this seal
            try:
                # One pipelined store burst for the coalesced backlog
                # (every blob here is reply-sized, i.e. <= the inline cap).
                self.plane.put_blobs_inline(batch)
            except Exception:
                # Store gone (shutdown) or a mid-batch error: fall back to
                # per-object puts so one bad blob can't strand the rest.
                for oid, blob in batch:
                    try:
                        self.plane.put_blob(oid, blob)
                    except Exception:
                        pass
            if batch:
                _events.emit("inline.seal", value=float(len(batch)))


class TaskEventLog:
    """Buffered task-event shipping (parity: task_event_buffer.h:188)."""

    def __init__(self, conductor_address: str, node_id: bytes, pid: int):
        self._events = []
        self._lock = threading.Lock()
        self._cli = get_client(conductor_address)
        self._node_hex = node_id.hex()
        self._pid = pid
        self._flusher = threading.Thread(target=self._loop, daemon=True,
                                         name="task-event-flusher")
        self._flusher.start()

    def record(self, task_id: bytes, name: str, kind: str,
               start: float, end: float, error: str = "") -> None:
        with self._lock:
            self._events.append({
                "task_id": task_id.hex(), "name": name, "kind": kind,
                "start": start, "end": end, "node_id": self._node_hex,
                "pid": self._pid, "error": error,
            })

    def _loop(self) -> None:
        while True:
            time.sleep(1.0)
            self.flush()

    def flush(self) -> None:
        with self._lock:
            events, self._events = self._events, []
        if events:
            try:
                self._cli.call("push_task_events", events=events)
            except Exception:
                pass


class WorkerService:
    """The worker's RPC surface (tasks pushed directly by submitters)."""

    # Pipelined frames dispatch INLINE on the channel's reader thread
    # (protocol._Handler.handle) instead of through the per-connection
    # executor. Safe here — and only here — because every pipelined
    # caller of this service is strictly request-at-a-time per channel:
    # the task submitter keeps one in-flight push per leased worker, and
    # actor pushers serialize on seqno. Control frames that must never
    # queue behind a running task (ping, cancel_task, kill_actor) arrive
    # classic on separate connections. Conductor/daemon services must NOT
    # set this: their channels carry long-polls that would head-of-line
    # block everything behind them.
    rpc_inline_pipelined = True

    def __init__(self, conductor_address: str, daemon_address: str,
                 store_socket: str, store_prefix: str, node_id: bytes):
        self.worker_id = WorkerID.from_random()
        self.conductor_address = conductor_address
        self.daemon_address = daemon_address
        self.node_id = node_id
        self.store = object_client.ShmClient(store_socket, store_prefix)
        self.plane = ObjectPlane(self.store, node_id, conductor_address,
                                 daemon_address=daemon_address)
        self._sealer = _LazySealer(self.plane)
        self._ilim_gen = None       # inline-return limit, config-cached
        self._ilim_v = -1
        self._ftmo_gen = None       # arg-fetch timeout, config-cached
        self._ftmo_v = 30.0
        self.events = TaskEventLog(conductor_address, node_id, os.getpid())
        self._fn_cache: Dict[str, Any] = {}
        self._exec_lock = threading.Lock()   # serial normal-task execution
        self._cancelled: set = set()
        # --- actor state (one dedicated actor per worker) ---
        self.actor_id: Optional[bytes] = None
        self.actor_instance: Any = None
        self.actor_class_name = ""
        self.actor_is_async = False
        self.actor_max_concurrency = 1
        self.actor_loop: Optional[asyncio.AbstractEventLoop] = None
        self.actor_pool = None
        # per-caller ordering (parity: actor_scheduling_queue.h)
        self._seq_lock = threading.Lock()
        self._seq_cv = threading.Condition(self._seq_lock)
        self._next_seq: Dict[bytes, int] = {}
        self._active_calls = 0   # in-flight pushes; gates process recycling
        # Pins taken over from callers for not-yet-run enqueued actor work;
        # released on kill/exit so a dead actor doesn't leak its arguments.
        self._taken_pins: Dict[bytes, int] = {}
        # Resident compiled-graph loops (dag/compiled.py) keyed by graph id.
        self._cgraph_loops: Dict[bytes, Any] = {}
        self._cgraph_lock = threading.Lock()
        self._shutdown = threading.Event()
        # Orphan watchdog: a worker whose NODE DAEMON is gone (daemon
        # process SIGKILLed, chaos test, host teardown race) must exit
        # rather than linger — an orphan herd's doomed reconnect loops
        # measurably tax the host, and nothing will ever lease it again.
        threading.Thread(target=self._daemon_watchdog, daemon=True,
                         name="daemon-watchdog").start()

    def _daemon_watchdog(self) -> None:
        misses = 0
        while not self._shutdown.wait(5.0):
            try:
                get_client(self.daemon_address).call("ping", _timeout=5.0)
                misses = 0
            except Exception:
                misses += 1
                if misses >= 3:
                    os._exit(1)

    # ------------------------------------------------------------------
    def _load_fn(self, function_id: str, blob: Optional[bytes]):
        fn = self._fn_cache.get(function_id)
        if fn is None:
            if blob is None:
                from ray_tpu import config
                blob = get_client(
                    self.conductor_address,
                    reconnect_s=config.get("gcs_rpc_reconnect_s")).call(
                    "get_function", function_id=function_id)
                if blob is None:
                    raise RuntimeError(
                        f"function {function_id} not found in function table")
            fn = serialization.loads(blob)
            self._fn_cache[function_id] = fn
        return fn

    def _fetch_timeout(self) -> float:
        # Bounded fetch: a dependency that was GC-freed or lost without
        # lineage must fail the task (visible to the caller) rather than
        # hang this worker forever. Cached against the config generation
        # (config.get walks os.environ; this sits on every task).
        from ray_tpu import config
        if self._ftmo_gen != config.generation:
            self._ftmo_v = config.get("worker_fetch_timeout_s")
            self._ftmo_gen = config.generation
        return self._ftmo_v

    def _resolve(self, args_blob: bytes,
                 inline_args: Optional[dict] = None):
        args, kwargs = serialization.loads(args_blob)
        if not args and not kwargs:
            return args, kwargs
        timeout = self._fetch_timeout()

        def rv(ref):
            if inline_args:
                # In-spec small arg (submit-side inliner): the serialized
                # value rode the task spec — no store fetch, no pin.
                blob = inline_args.get(store_key(ref.id.binary()))
                if blob is not None:
                    return serialization.deserialize(memoryview(blob))
            try:
                return self.plane.get_value(ref.id, timeout=timeout)
            except GetTimeoutError:
                raise ObjectLostError(
                    ref.id.hex(), f"task argument unavailable after "
                    f"{timeout}s (freed or lost)") from None

        # Shared rule with the submit side (task_spec.top_level_ref_args):
        # only TOP-LEVEL ref args resolve by value.
        return task_spec.resolve_task_args(args, kwargs, rv)

    def _flush_refs(self) -> None:
        """Ship this process's pending refcount events to the conductor
        BEFORE acking a push RPC — the submitter releases its in-flight
        argument pins on the ack, so any +1 this execution produced (user
        code keeping a borrowed ref) must be in the ledger first
        (core/refcount.py ordering protocol)."""
        t = _refs_mod._tracker
        if t is not None:
            t.flush()

    def _inline_limit(self) -> int:
        """Reply-carried return size cap (-1 = feature off); cached against
        the config generation (this sits on every task return)."""
        from ray_tpu import config
        if self._ilim_gen != config.generation:
            self._ilim_v = (int(config.get("max_inline_object_bytes"))
                            if config.get("task_inline_returns") else -1)
            self._ilim_gen = config.generation
        return self._ilim_v

    def _emit_return(self, oid: ObjectID, value: Any, collect) -> None:
        """Store one return value. With ``collect`` (reply-carried mode),
        results at or below max_inline_object_bytes ride the push reply as
        {"data": blob} entries and seal into the store lazily; larger ones
        seal now and reply {"stored": True}. collect=None keeps the
        classic store-now behavior (async/pool actor paths, whose acks
        predate execution)."""
        if collect is None:
            self.plane.put_value(oid, value)
            return
        limit = self._inline_limit()
        total, segments, refs = serialization.serialize_segments(value)
        if limit < 0 or total > limit:
            self.plane.put_segments(oid, total, segments, refs)
            collect.append({"stored": True})
            return
        blob = segments[0] if len(segments) == 1 else b"".join(segments)
        if refs:
            t = _refs_mod._tracker
            if t is not None:
                # flush=False: _flush_refs() runs before the ack AND before
                # the seal enqueue, so the children's +1s are durable
                # before the parent becomes readable anywhere — the same
                # invariant add_children's default sync flush upholds,
                # batched into one pre-ack RPC instead of one per return.
                t.add_children(self.plane._key(oid),
                               [store_key(r.id.binary()) for r in refs],
                               flush=False)
        # Fault point: the inlining decision (a "raise" rule fails the
        # task through the normal error path; see also task.return.seal).
        fault_plane.fire("task.reply.inline", oid=oid.hex())
        collect.append({"data": blob, "_oid": oid})

    def _store_returns(self, task_id: bytes, num_returns: int, result: Any,
                       collect=None):
        tid = TaskID(task_id)
        if num_returns == 1:
            self._emit_return(tid.object_id_for_return(0), result, collect)
            return
        vals = list(result)
        if len(vals) != num_returns:
            err = TaskError.from_exception(ValueError(
                f"Task declared num_returns={num_returns} but returned "
                f"{len(vals)} values"))
            if collect is not None:
                collect[:] = []
            for i in range(num_returns):
                self._emit_return(tid.object_id_for_return(i), err, collect)
            return
        for i, v in enumerate(vals):
            self._emit_return(tid.object_id_for_return(i), v, collect)

    def _fail_returns(self, task_id: bytes, num_returns: int, exc, desc: str,
                      collect=None):
        err = exc if isinstance(exc, TaskError) else TaskError.from_exception(
            exc, desc)
        tid = TaskID(task_id)
        for i in range(num_returns):
            try:
                self._emit_return(tid.object_id_for_return(i), err, collect)
            except BaseException:  # noqa: BLE001 - fallback error report; caller must unblock
                # The error object itself failed to serialize/store: fall
                # back to a bare TaskError so the caller still unblocks.
                self._emit_return(tid.object_id_for_return(i),
                                  TaskError(repr(err), desc), collect)

    def _queue_seals(self, per_task_entries) -> None:
        """Strip the private _oid markers from reply entries and hand the
        (oid, blob) pairs to the lazy sealer. Called AFTER _flush_refs():
        a remotely-readable (sealed) parent must never precede its
        children's durable +1s."""
        seals = []
        for entries in per_task_entries:
            for e in entries:
                oid = e.pop("_oid", None)
                if oid is not None:
                    seals.append((oid, e["data"]))
        if seals:
            self._sealer.enqueue(seals)

    # ------------------------------------------------------------------
    # normal tasks
    # ------------------------------------------------------------------
    def _exec_one(self, task_id: bytes, function_id: str,
                  function_blob: Optional[bytes], args_blob: bytes,
                  num_returns: int, name: str,
                  trace_ctx: Optional[dict] = None,
                  inline_args: Optional[dict] = None,
                  collect=None) -> None:
        """Execute one task body; returns are stored (or collected into the
        push reply) before this returns. Caller holds _exec_lock (serial
        normal-task execution)."""
        start = time.time()
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            from ray_tpu.core.exceptions import TaskCancelledError
            self._fail_returns(task_id, num_returns,
                               TaskCancelledError("task cancelled"), name,
                               collect)
            return
        error = ""
        try:
            # Fault point: mid-task kill. A "crash" rule here os._exit()s
            # between dequeue and result-store — the window where only
            # lineage reconstruction (or task retries) can save the caller.
            fault_plane.fire("worker.task.exec", name=name)
            fn = self._load_fn(function_id, function_blob)
            args, kwargs = self._resolve(args_blob, inline_args)
            result = fn(*args, **kwargs)
            self._store_returns(task_id, num_returns, result, collect)
        except BaseException as e:  # noqa: BLE001 - delivered via refs
            error = repr(e)
            # A partially-collected reply must not misalign the entry list
            # (one entry per return, in order).
            if collect is not None:
                collect[:] = []
            try:
                self._fail_returns(task_id, num_returns, e, name, collect)
            except BaseException:  # noqa: BLE001 - injected double fault
                if collect is not None:
                    collect[:] = []
        end = time.time()
        self.events.record(task_id, name, "task", start, end, error)
        _events.emit("task.exec", task_id.hex(), value=end - start,
                     attrs={"task": name, "error": error} if error
                     else {"task": name})
        if trace_ctx is not None:
            from ray_tpu.util import tracing
            ctx = tracing.new_context(parent=trace_ctx)
            attrs = {"task": name, "worker_pid": os.getpid()}
            if error:
                attrs["error"] = error
            tracing.record("task.execute", start, end, ctx, attrs)

    def rpc_push_task(self, task_id: bytes, function_id: str,
                      function_blob: Optional[bytes], args_blob: bytes,
                      num_returns: int, name: str = "") -> dict:
        """Single-task compat shim over the batch path."""
        return self.rpc_push_task_batch([{
            "task_id": task_id, "function_id": function_id,
            "function_blob": function_blob, "args_blob": args_blob,
            "num_returns": num_returns, "name": name}])

    def rpc_push_task_batch(self, tasks: list) -> dict:
        """Execute a coalesced batch serially; one ack for all (the
        submitter batches deep queues — core/runtime_cluster.py _pump).
        The reply carries each task's small returns inline ({"data": blob}
        per return, in return order) — the caller seeds its object plane
        from them and never touches the store; the worker seals the same
        blobs lazily (_LazySealer) so the objects stay full citizens."""
        returns: Dict[bytes, list] = {}
        with self._exec_lock:
            for t in tasks:
                entries: list = []
                self._exec_one(t["task_id"], t["function_id"],
                               t.get("function_blob"), t["args_blob"],
                               t["num_returns"], t.get("name", ""),
                               trace_ctx=t.get("trace_ctx"),
                               inline_args=t.get("inline_args"),
                               collect=entries)
                returns[t["task_id"]] = entries
        self._flush_refs()
        self._queue_seals(returns.values())
        # Traced spans ship via the background event flusher (events.py) —
        # the old synchronous tracing.flush here put a conductor RPC on
        # every traced batch ack.
        return {"ok": True, "node_id": self.node_id, "returns": returns}

    def rpc_cancel_task(self, task_id: bytes) -> None:
        self._cancelled.add(task_id)

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def rpc_create_actor(self, actor_id: bytes, spec: dict,
                         incarnation: int) -> dict:
        start = time.time()
        try:
            cls = self._load_fn(spec["function_id"], spec.get("class_blob"))
            args, kwargs = self._resolve(spec["args_blob"])
            instance = cls(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            import pickle
            try:
                blob = pickle.dumps(TaskError.from_exception(
                    e, spec.get("class_name", "") + ".__init__"))
            except Exception:
                blob = pickle.dumps(TaskError(repr(e), ""))
            get_client(self.conductor_address).call(
                "actor_creation_failed", actor_id=actor_id,
                incarnation=incarnation, error_blob=blob)
            return {"ok": False}
        self.actor_id = actor_id
        self.actor_instance = instance
        self.actor_class_name = spec.get("class_name", "")
        self.actor_is_async = spec.get("is_async", False)
        self.actor_max_concurrency = spec["opts"].get("max_concurrency", 1)
        if self.actor_is_async:
            self.actor_loop = asyncio.new_event_loop()
            threading.Thread(target=self.actor_loop.run_forever,
                             daemon=True, name="actor-loop").start()
        elif self.actor_max_concurrency > 1:
            from concurrent.futures import ThreadPoolExecutor
            self.actor_pool = ThreadPoolExecutor(
                max_workers=self.actor_max_concurrency,
                thread_name_prefix="actor")
        get_client(self.conductor_address).call(
            "actor_started", actor_id=actor_id, address=self.address,
            node_id=self.node_id, incarnation=incarnation)
        self.events.record(actor_id + b"\x00" * 4,
                           self.actor_class_name + ".__init__",
                           "actor_creation", start, time.time())
        return {"ok": True}

    def _wait_turn(self, caller_id: bytes, seqno: int) -> bool:
        """Block until this seqno's turn. Returns False for a duplicate:
        a caller that lost the push reply resends the same seqno, which by
        then has already executed (its returns are sealed in the store) —
        re-executing would double-apply side effects and waiting would
        deadlock (next_seq has moved past it)."""
        with self._seq_cv:
            while self._next_seq.get(caller_id, 0) < seqno:
                self._seq_cv.wait(1.0)
            return self._next_seq.get(caller_id, 0) == seqno

    def _done_turn(self, caller_id: bytes, seqno: int) -> None:
        with self._seq_cv:
            nxt = self._next_seq.get(caller_id, 0)
            if seqno >= nxt:
                self._next_seq[caller_id] = seqno + 1
            self._seq_cv.notify_all()

    def rpc_push_actor_task(self, task_id: bytes, caller_id: bytes,
                            seqno: int, method_name: str, args_blob: bytes,
                            num_returns: int,
                            arg_pins: Optional[list] = None,
                            actor_id: Optional[bytes] = None,
                            inline_args: Optional[dict] = None) -> dict:
        """Ordered actor call (per-caller seqno; see class docstring).
        ``actor_id`` guards against a stale address: a recycled worker may
        host a DIFFERENT actor at the address a slow caller cached, and a
        push for the dead tenant must fail, not hit the new instance."""
        if actor_id is not None and actor_id != self.actor_id:
            raise RuntimeError("actor no longer hosted on this worker "
                               "(stale address after recycle)")
        if self.actor_instance is None:
            raise RuntimeError("no actor hosted on this worker")
        with self._seq_lock:
            self._active_calls += 1
        try:
            return self._push_actor_task(task_id, caller_id, seqno,
                                         method_name, args_blob,
                                         num_returns, arg_pins, inline_args)
        finally:
            with self._seq_lock:
                self._active_calls -= 1

    def _push_actor_task(self, task_id: bytes, caller_id: bytes,
                         seqno: int, method_name: str, args_blob: bytes,
                         num_returns: int,
                         arg_pins: Optional[list] = None,
                         inline_args: Optional[dict] = None) -> dict:
        name = f"{self.actor_class_name}.{method_name}"
        start = time.time()
        error = ""

        def unpin_args():
            if not arg_pins:
                return
            t = _refs_mod._tracker
            if t is not None:
                t.unpin_all(arg_pins)
            with self._seq_lock:
                for k in arg_pins:
                    if self._taken_pins.get(k, 0) > 1:
                        self._taken_pins[k] -= 1
                    else:
                        self._taken_pins.pop(k, None)

        def run_sync(collect=None):
            err = ""
            if task_id in self._cancelled:
                # Cancelled before execution started (rt.cancel on an
                # actor-task ref — e.g. a serve deadline): store the
                # cancellation error, never run user code.
                self._cancelled.discard(task_id)
                from ray_tpu.core.exceptions import TaskCancelledError
                self._fail_returns(task_id, num_returns,
                                   TaskCancelledError("actor task cancelled"),
                                   name, collect)
                return "cancelled"
            try:
                # Fault point: kill/fail mid-actor-task — after the seqno
                # turn was taken, before the result stores. Exercises the
                # restart FSM + max_task_retries resubmission. ``method``
                # is the bare method name (``name`` is module-qualified,
                # unwieldy for match filters).
                fault_plane.fire("worker.actor.exec", name=name,
                                 method=method_name)
                args, kwargs = self._resolve(args_blob, inline_args)
                m = getattr(self.actor_instance, method_name)
                result = m(*args, **kwargs)
                self._store_returns(task_id, num_returns, result, collect)
            except BaseException as e:  # noqa: BLE001
                err = repr(e)
                if collect is not None:
                    collect[:] = []
                try:
                    self._fail_returns(task_id, num_returns, e, name,
                                       collect)
                except BaseException:  # noqa: BLE001 - injected dbl fault
                    if collect is not None:
                        collect[:] = []
            return err

        def take_over_pins():
            """Enqueue-ack paths: the caller unpins its in-flight argument
            pins when this RPC returns, but execution happens later — take
            the pins over HERE (flushed before the ack) so the argument
            objects survive the gap (core/refcount.py ordering). Tracked in
            _taken_pins so a kill before execution releases them."""
            if not arg_pins:
                return
            t = _refs_mod._tracker
            if t is not None:
                t.pin_all(arg_pins)
            with self._seq_lock:
                for k in arg_pins:
                    self._taken_pins[k] = self._taken_pins.get(k, 0) + 1

        if self.actor_is_async:
            # Ordered start, concurrent awaits (parity: async actors).
            async def run_async():
                err = ""
                if task_id in self._cancelled:
                    self._cancelled.discard(task_id)
                    from ray_tpu.core.exceptions import TaskCancelledError
                    self._fail_returns(
                        task_id, num_returns,
                        TaskCancelledError("actor task cancelled"), name)
                    unpin_args()
                    return "cancelled"
                try:
                    loop = asyncio.get_running_loop()
                    args, kwargs = await loop.run_in_executor(
                        None, lambda: self._resolve(args_blob, inline_args))
                    m = getattr(self.actor_instance, method_name)
                    result = m(*args, **kwargs)
                    if inspect.isawaitable(result):
                        result = await result
                    self._store_returns(task_id, num_returns, result)
                except BaseException as e:  # noqa: BLE001
                    err = repr(e)
                    self._fail_returns(task_id, num_returns, e, name)
                finally:
                    unpin_args()
                return err

            if not self._wait_turn(caller_id, seqno):
                return {"ok": True, "duplicate": True}
            take_over_pins()
            asyncio.run_coroutine_threadsafe(run_async(), self.actor_loop)
            self._done_turn(caller_id, seqno)
            # Ack on enqueue: concurrent awaits must overlap, so completion
            # is observed through the object store, not this reply.
            return {"ok": True, "enqueued": True}
        elif self.actor_pool is not None:
            # max_concurrency > 1: out-of-order execution is allowed
            # (parity: out_of_order_actor_scheduling_queue.h).
            if not self._wait_turn(caller_id, seqno):
                return {"ok": True, "duplicate": True}
            take_over_pins()

            def run_and_unpin():
                try:
                    run_sync()
                finally:
                    unpin_args()

            self.actor_pool.submit(run_and_unpin)
            self._done_turn(caller_id, seqno)
            return {"ok": True, "enqueued": True}
        else:
            # Sync actors ack AFTER execution, so the reply can carry the
            # small returns inline (same contract as push_task_batch); the
            # caller's call_async future completes with the value in hand.
            # enqueued/duplicate acks above carry NO returns — the caller
            # falls back to observing the store.
            if not self._wait_turn(caller_id, seqno):
                return {"ok": True, "duplicate": True}
            entries: list = []
            try:
                error = run_sync(entries)
            finally:
                self._done_turn(caller_id, seqno)
            self._flush_refs()
            self._queue_seals([entries])
        self.events.record(task_id, name, "actor_task", start, time.time(),
                           error)
        return {"ok": True, "node_id": self.node_id, "returns": entries}

    def _release_taken_pins(self) -> None:
        t = _refs_mod._tracker
        with self._seq_lock:
            pins, self._taken_pins = self._taken_pins, {}
        if t is not None and pins:
            for k, n in pins.items():
                t.unpin_all([k] * n)
            t.flush()

    def _recyclable(self) -> bool:
        """A process may be returned to the daemon's idle pool only when
        nothing of the dead actor can leak into the next tenant: sync-only
        (an event loop / thread pool may still be running user coroutines),
        and no push in flight."""
        from ray_tpu import config
        if not config.get("actor_worker_recycle"):
            return False
        if self.actor_is_async or self.actor_pool is not None:
            return False
        with self._seq_lock:
            return self._active_calls == 0

    def _reset_actor_state(self) -> None:
        self._stop_cgraph_loops()   # loops hold the dying actor instance
        with self._seq_lock:
            self.actor_id = None
            self.actor_instance = None
            self.actor_class_name = ""
            self.actor_is_async = False
            self.actor_max_concurrency = 1
            self._next_seq.clear()   # new tenant's callers restart at seqno 0
            self._taken_pins.clear()
            self._cancelled.clear()
            self._seq_cv.notify_all()

    def rpc_kill_actor(self, actor_id: bytes) -> dict:
        if actor_id != self.actor_id:
            # Previous tenant (recycled away) or duplicate kill retry after
            # the state was already reset: nothing to do, and killing the
            # process now could take down an innocent new tenant.
            return {"ok": True, "stale": True}
        self.events.flush()
        self._stop_cgraph_loops()
        self._release_taken_pins()
        recycled = False
        if self._recyclable():
            # Reset BEFORE offering the process back: the daemon may hand
            # this worker to a new create_actor the instant it pools it.
            self._reset_actor_state()
            try:
                resp = get_client(self.daemon_address).call(
                    "actor_exited", actor_id=actor_id, recycle=True)
                recycled = bool(resp and resp.get("recycled"))
            except Exception:
                recycled = False
        else:
            try:
                get_client(self.daemon_address).call("actor_exited",
                                                     actor_id=actor_id)
            except Exception:
                pass
        if recycled:
            return {"ok": True, "recycled": True}
        self._shutdown.set()
        threading.Timer(0.1, lambda: os._exit(0)).start()
        return {"ok": True}

    def rpc_ping(self) -> str:
        return "pong"

    # -- compiled execution graphs (dag/compiled.py) ---------------------

    def rpc_install_cgraph_loop(self, graph_id: bytes, plan: dict) -> dict:
        """Install a resident compiled-graph loop on this actor worker.
        Creates the actor's input rings (consumer-side ownership) and
        starts the loop thread; normal .remote() task service continues to
        run alongside it."""
        if self.actor_instance is None:
            return {"ok": False, "error": "no actor hosted on this worker"}
        from ray_tpu.dag.compiled import CGraphWorkerLoop, ScheduledWorkerLoop
        cls = (ScheduledWorkerLoop if plan.get("mode") == "schedule"
               else CGraphWorkerLoop)
        with self._cgraph_lock:
            if graph_id in self._cgraph_loops:
                return {"ok": True, "dup": True}
            loop = cls(self, graph_id, plan)
            self._cgraph_loops[graph_id] = loop
        loop.start()
        return {"ok": True}

    def rpc_teardown_cgraph_loop(self, graph_id: bytes) -> dict:
        with self._cgraph_lock:
            loop = self._cgraph_loops.pop(graph_id, None)
        if loop is None:
            return {"ok": True, "stale": True}
        loop.stop()
        return {"ok": True}

    def _stop_cgraph_loops(self) -> None:
        with self._cgraph_lock:
            loops, self._cgraph_loops = list(self._cgraph_loops.values()), {}
        for loop in loops:
            try:
                loop.stop(join_timeout=1.0)
            except Exception:
                pass

    def rpc_debug_state(self) -> dict:
        """Structured debug-state dump (the worker's share of raylet
        debug_state.txt: execution queues, actor tenancy, seal backlog)."""
        with self._seq_lock:
            active = self._active_calls
            taken_pins = len(self._taken_pins)
            ordered_callers = len(self._next_seq)
            actor_id = self.actor_id
        with self._sealer._cv:
            seal_backlog = len(self._sealer._q)
        return {
            "role": "worker",
            "worker_id": self.worker_id.binary().hex(),
            "node_id": self.node_id.hex(),
            "pid": os.getpid(),
            "actor": {
                "actor_id": actor_id.hex() if actor_id else None,
                "class_name": self.actor_class_name,
                "is_async": self.actor_is_async,
                "max_concurrency": self.actor_max_concurrency,
                "active_calls": active,
                "ordered_callers": ordered_callers,
                "taken_pins": taken_pins,
            },
            "cancelled_pending": len(self._cancelled),
            "cgraph_loops": [lp.debug_state()
                             for lp in self._cgraph_loops.values()],
            "fn_cache_entries": len(self._fn_cache),
            "lazy_seal_backlog": seal_backlog,
            "object_plane": self.plane.debug_state(),
        }

    def rpc_profile(self, duration_s: float = 1.0,
                    interval_s: float = 0.01) -> str:
        """On-demand sampling profile of this worker -> collapsed stacks
        (util/profiler.py; parity: reporter/profile_manager.py py-spy)."""
        from ray_tpu.util.profiler import collect
        return collect(duration_s=min(float(duration_s), 30.0),
                       interval_s=max(float(interval_s), 0.001))

    def rpc_exit(self) -> dict:
        self._stop_cgraph_loops()
        self._release_taken_pins()
        self._shutdown.set()
        threading.Timer(0.05, lambda: os._exit(0)).start()
        return {"ok": True}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--conductor", required=True)
    ap.add_argument("--daemon", required=True)
    ap.add_argument("--store-socket", required=True)
    ap.add_argument("--store-prefix", required=True)
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--token", required=True)
    args = ap.parse_args()
    # Adopt the parent's system-config overrides (RT_SYSTEM_CONFIG_JSON):
    # flag changes — including a loaded fault plan — follow the spawn.
    from ray_tpu import config
    try:
        config.load_from_env()
    except Exception:
        pass  # an unknown flag from a mismatched parent must not kill boot
    prof = os.environ.get("RTPU_WORKER_STARTUP_PROF")
    marks = [("start", time.perf_counter())]
    node_id = bytes.fromhex(args.node_id)
    svc = WorkerService(args.conductor, args.daemon, args.store_socket,
                        args.store_prefix, node_id)
    marks.append(("service", time.perf_counter()))
    server = RpcServer(svc)
    svc.address = server.address
    marks.append(("rpc_server", time.perf_counter()))
    # Connect the in-process public API so user code can submit nested work.
    from ray_tpu.core import api
    from ray_tpu.core.runtime_cluster import ClusterRuntime
    marks.append(("runtime_import", time.perf_counter()))
    api._runtime = ClusterRuntime.for_worker(
        conductor_address=args.conductor, daemon_address=args.daemon,
        store=svc.store, plane=svc.plane, node_id=node_id)
    marks.append(("for_worker", time.perf_counter()))
    get_client(args.daemon).call(
        "register_worker", token=args.token,
        worker_id=svc.worker_id.binary(), address=server.address,
        pid=os.getpid())
    marks.append(("registered", time.perf_counter()))
    if prof:
        base = marks[0][1]
        print("STARTUP " + " ".join(
            f"{k}={1000 * (ts - base):.1f}ms" for k, ts in marks[1:]),
            flush=True)
    svc._shutdown.wait()
    try:
        svc.plane.stop()   # drain batched location registrations
    except Exception:
        pass


if __name__ == "__main__":
    main()
