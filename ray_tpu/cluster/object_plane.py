"""Object plane: local shm store + remote pull + location directory.

Role parity: the core worker's plasma provider + PullManager
(core_worker.cc:1307 Get -> plasma -> raylet pull, pull_manager.h:52).
Shared by the driver runtime and by worker processes: values are serialized
with out-of-band buffers (core/serialization.py), stored in the node's
shmstored, registered in the conductor's object directory, and pulled
node-to-node in chunks when non-local.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.cluster import object_client
from ray_tpu.cluster.node_daemon import CHUNK_SIZE
from ray_tpu.cluster.protocol import get_client
from ray_tpu.core import serialization
from ray_tpu.core.exceptions import GetTimeoutError, ObjectLostError
from ray_tpu.core.ids import ObjectID, store_key


class _ByteBudget:
    """Admission control for concurrent pulls (pull_manager.h:52 role):
    bounds total in-flight pull bytes so N parallel fetches of large
    objects can't blow the local store. An oversized single request is
    admitted alone (never deadlocks)."""

    def __init__(self, cap: int):
        self.cap = cap
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._used > 0 and self._used + n > self.cap:
                self._cv.wait(1.0)
            self._used += n

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class ObjectPlane:
    def __init__(self, store: object_client.ShmClient, node_id: bytes,
                 conductor_address: str):
        from ray_tpu import config
        self.store = store
        self.node_id = node_id
        self.conductor = get_client(
            conductor_address,
            reconnect_s=config.get("gcs_rpc_reconnect_s"))
        self._pull_locks: Dict[bytes, threading.Lock] = {}
        self._pull_guard = threading.Lock()
        self._pull_budget = _ByteBudget(
            config.get("max_concurrent_pull_bytes"))

    # -- write ----------------------------------------------------------
    def put_value(self, oid: ObjectID, value: Any) -> int:
        """Serialize + store, copying large buffers once (straight into the
        shm mapping). Contained ObjectRefs are registered as children so
        the stored object keeps them alive (reference_count.h nested refs).
        """
        total, segments, refs = serialization.serialize_segments(value)
        key = self._key(oid)
        if refs:
            from ray_tpu.core import refs as _refs_mod
            t = _refs_mod._tracker
            if t is not None:
                t.add_children(key, [store_key(r.id.binary()) for r in refs])
        try:
            buf = self.store.create(key, total)
            off = 0
            for seg in segments:
                m = memoryview(seg)
                buf[off:off + m.nbytes] = m
                off += m.nbytes
            self.store.seal(key)
        except object_client.ObjectStoreError as e:
            if "already exists" not in str(e):
                raise
        self.conductor.call("add_object_location", oid=key,
                            node_id=self.node_id)
        return total

    def put_blob(self, oid: ObjectID, blob: bytes) -> int:
        key = self._key(oid)
        try:
            buf = self.store.create(key, len(blob))
            if len(blob):
                buf[:] = blob
            self.store.seal(key)
        except object_client.ObjectStoreError as e:
            if "already exists" not in str(e):
                raise
        self.conductor.call("add_object_location", oid=key,
                            node_id=self.node_id)
        return len(blob)

    # -- read -----------------------------------------------------------
    def _key(self, oid: ObjectID) -> bytes:
        # shmstored keys are 16 bytes; ObjectIDs are 20 (task id + index).
        return store_key(oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        try:
            return self.store.contains(self._key(oid))
        except (BrokenPipeError, ConnectionError, OSError):
            # The store daemon is gone (runtime shutting down, or a chaos
            # test killed it): "not present locally" is the right answer —
            # readers fall back to the object directory / recovery.
            return False

    def get_value(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        # Small sealed LOCAL objects come back inline in ONE store round
        # trip (no get+release pair, no mmap) — the dominant pattern when
        # ray_tpu.get() collects many small task results.
        data = self.store.get_inline(self._key(oid))
        if data is not None:
            return serialization.deserialize(memoryview(data))
        view = self.get_view(oid, timeout=timeout)
        value = serialization.deserialize(view)
        # NOTE: buffer-backed values (numpy arrays) stay zero-copy views over
        # the shm mapping; the mapping outlives release() (mmap semantics).
        self.store.release(self._key(oid))
        return value

    def get_view(self, oid: ObjectID,
                 timeout: Optional[float] = None) -> memoryview:
        key = self._key(oid)
        # Fast path: local.
        view = self.store.get(key, timeout=0.0)
        if view is not None:
            return view
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = 2.0 if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(
                    f"timed out waiting for object {oid.hex()}")
            loc = self.conductor.call("locate_object", oid=key,
                                      timeout=min(remaining, 2.0))
            view = self.store.get(key, timeout=0.0)
            if view is not None:
                return view
            for node in loc["nodes"]:
                if node["node_id"] == self.node_id:
                    continue
                if self._pull(key, node["address"]):
                    view = self.store.get(key, timeout=0.0)
                    if view is not None:
                        return view
            # No location known yet (still being computed) -> loop.

    def _pull(self, key: bytes, remote_addr: str) -> bool:
        """Chunked pull of one object from a remote daemon into local shm.

        Single-flight per object: concurrent getters wait on the same pull.
        """
        with self._pull_guard:
            lock = self._pull_locks.setdefault(key, threading.Lock())
        with lock:
            if self.store.contains(key):
                return True
            cli = get_client(remote_addr)
            admitted = 0
            try:
                info = cli.call("object_info", oid=key)
                if not info["found"]:
                    return False
                size = info["size"]
                self._pull_budget.acquire(size)
                admitted = size
                buf = self.store.create(key, size)
                off = 0
                while off < size:
                    n = min(CHUNK_SIZE, size - off)
                    chunk = cli.call("fetch_chunk", oid=key, offset=off, size=n)
                    buf[off:off + n] = chunk
                    off += n
                self.store.seal(key)
            except object_client.ObjectStoreError as e:
                if "already exists" in str(e):
                    return True
                raise
            except Exception:
                return False
            finally:
                if admitted:
                    self._pull_budget.release(admitted)
            self.conductor.call("add_object_location", oid=key,
                                node_id=self.node_id)
            return True

    def free(self, oid: ObjectID) -> None:
        self.conductor.call("free_object", oid=self._key(oid))
