"""Object plane: local shm store + remote pull + location directory.

Role parity: the core worker's plasma provider + PullManager
(core_worker.cc:1307 Get -> plasma -> raylet pull, pull_manager.h:52).
Shared by the driver runtime and by worker processes: values are serialized
with out-of-band buffers (core/serialization.py), stored in the node's
shmstored, registered in the conductor's object directory, and pulled
node-to-node in chunks when non-local.
"""

from __future__ import annotations

import logging
import mmap
import os
import random
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import FIRST_COMPLETED
from concurrent.futures import TimeoutError as _FutureTimeout
from concurrent.futures import wait as _futures_wait
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.cluster import fault_plane, object_client
from ray_tpu.cluster.protocol import ConnectionLost, RpcError, get_client
from ray_tpu.core import serialization
from ray_tpu.core.exceptions import GetTimeoutError, ObjectLostError
from ray_tpu.core.ids import ObjectID, store_key
from ray_tpu.util import events as _events
from ray_tpu.util import lockcheck

# Batch-get miss marker (a stored value may legitimately be None).
MISS = object()

logger = logging.getLogger(__name__)

_loc_dropped_counter = None


def _count_dropped_registrations(n: int) -> None:
    global _loc_dropped_counter
    if _loc_dropped_counter is None:
        from ray_tpu.util.metrics import Counter
        _loc_dropped_counter = Counter(
            "location_registrations_dropped",
            "Object-location registrations discarded because the batcher's "
            "buffer overflowed during a conductor outage.")
    _loc_dropped_counter.inc(n)


class _ByteBudget:
    """Admission control for concurrent pulls (pull_manager.h:52 role):
    bounds total in-flight pull bytes so N parallel fetches of large
    objects can't blow the local store. An oversized single request is
    admitted alone (never deadlocks).

    Waiters admit in FIFO order: only the head of the queue may take
    budget, so a large pull gets the next big-enough window instead of
    being starved forever by a stream of small requests slipping past it.
    """

    def __init__(self, cap: int):
        self.cap = cap
        self._used = 0
        self._cv = threading.Condition(
            lockcheck.named_lock("plane.pull_budget"))
        self._queue: "deque[object]" = deque()

    def acquire(self, n: int) -> None:
        ticket = object()
        with self._cv:
            self._queue.append(ticket)
            while self._queue[0] is not ticket or \
                    (self._used > 0 and self._used + n > self.cap):
                self._cv.wait(1.0)
            self._queue.popleft()
            self._used += n
            self._cv.notify_all()  # the next head may also fit

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class _InlineCache:
    """Caller-side cache of reply-carried small results (the reference's
    "direct call" objects, transport/direct_actor_transport.cc role).

    A push reply can carry a return value before the producing worker has
    sealed it into the store; the owner parks getters on the PENDING table
    and completes them straight from the reply — no store round trip, no
    conductor locate. Entries are serialized blobs (each get deserializes a
    fresh copy, same isolation as a store read), LRU-bounded by byte
    budget, and dropped eagerly when the local refcount hits zero."""

    def __init__(self, max_bytes: int):
        self._cv = threading.Condition()
        self.max_bytes = max_bytes
        self._blobs: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._nbytes = 0
        self._pending: set = set()

    # -- pending returns (futures completed by the push reply) ---------
    def add_pending(self, keys) -> None:
        with self._cv:
            self._pending.update(keys)

    def resolve(self, key: bytes) -> None:
        """The reply said this return is store-backed (or terminal): stop
        parking getters on the reply and let them take the store path."""
        with self._cv:
            if key in self._pending:
                self._pending.discard(key)
                self._cv.notify_all()

    def is_pending(self, key: bytes) -> bool:
        with self._cv:
            return key in self._pending

    def wait_resolved(self, key: bytes, timeout: float) -> bool:
        """Park until ``key`` leaves the pending state (seeded from a
        reply, resolved to store-backed, or dropped). False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while key in self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    # -- blob cache ----------------------------------------------------
    def seed(self, key: bytes, blob: bytes) -> None:
        with self._cv:
            old = self._blobs.pop(key, None)
            if old is not None:
                self._nbytes -= len(old)
            self._blobs[key] = blob
            self._nbytes += len(blob)
            while self._nbytes > self.max_bytes and self._blobs:
                _, v = self._blobs.popitem(last=False)
                self._nbytes -= len(v)
            self._pending.discard(key)
            self._cv.notify_all()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._cv:
            blob = self._blobs.get(key)
            if blob is not None:
                self._blobs.move_to_end(key)
            return blob

    def has(self, key: bytes) -> bool:
        with self._cv:
            return key in self._blobs

    def drop(self, key: bytes) -> None:
        with self._cv:
            blob = self._blobs.pop(key, None)
            if blob is not None:
                self._nbytes -= len(blob)
            self._pending.discard(key)
            self._cv.notify_all()


class _LocationBatcher:
    """Coalesces add_object_location registrations into one conductor RPC
    per ~5ms burst window. A task-result-heavy worker was spending a
    synchronous conductor round trip PER RESULT — at thousands of results/s
    that RPC dominates completion throughput. Registration becomes eventual
    (bounded by the flush window): same-node readers never notice (they hit
    the local store directly) and cross-node readers long-poll the
    directory anyway.

    Entries may target a node OTHER than our own: a caller that received a
    reply-carried inline result pre-registers the PRODUCER's node as the
    location so remote consumers can discover the (lazily sealed) copy —
    or get a deterministic probe-miss -> lost verdict if the producer died
    before sealing."""

    # 5ms: matches the refcount stream's flush cadence — one background
    # conductor RPC per window from each plane, not one per 2ms (measured
    # against the task ping-pong on a 1-CPU head: the conductor handler
    # work comes straight out of the driver/worker's cycle budget).
    _WINDOW_S = 0.005

    def __init__(self, conductor, node_id: bytes):
        self._conductor = conductor
        self._node_id = node_id
        self._buf: list = []    # (node_id, key) pairs, arrival order
        self._lock = lockcheck.named_lock("plane.loc_batch")
        self._event = threading.Event()
        self._stopped = False
        self._drop_logged = False
        self.dropped_total = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="loc-batch")
        self._thread.start()

    _MAX_BUFFER = 262_144  # registrations kept across a conductor outage

    def add(self, key: bytes, node_id: Optional[bytes] = None,
            device: str = "") -> None:
        with self._lock:
            self._buf.append((node_id or self._node_id, key, device))
        self._event.set()

    def _send(self, batch: list) -> None:
        by_node: Dict[bytes, list] = {}
        for nid, key, device in batch:
            by_node.setdefault(nid, []).append((key, device))
        for nid, entries in by_node.items():
            keys = [k for k, _ in entries]
            if any(d for _, d in entries):
                self._conductor.call(
                    "add_object_locations", oids=keys, node_id=nid,
                    devices=[d for _, d in entries])
            else:
                self._conductor.call("add_object_locations", oids=keys,
                                     node_id=nid)

    def _loop(self) -> None:
        backoff = self._WINDOW_S
        while not self._stopped:
            # Event-driven: block until the FIRST add (zero idle wakeups —
            # a polling loop here costs real throughput on small hosts),
            # then sleep one short window so followers coalesce.
            self._event.wait()
            if self._stopped:
                return
            time.sleep(backoff)
            self._event.clear()
            with self._lock:
                batch, self._buf = self._buf, []
            if not batch:
                continue
            try:
                self._send(batch)
                backoff = self._WINDOW_S
            except Exception:
                # Conductor unreachable (failover window): back off up to
                # 1s instead of hammering at the burst cadence, and bound
                # the buffer — after reconnection the daemon re-advertises
                # its whole store inventory anyway, so dropped entries are
                # recovered by that replay. Dropping is still an eventual-
                # consistency gamble (a driver-side plane has no inventory
                # replay), so it must be observable, not silent.
                backoff = min(backoff * 4, 1.0)
                with self._lock:
                    keep = (batch + self._buf)[-self._MAX_BUFFER:]
                    dropped = len(batch) + len(self._buf) - len(keep)
                    self._buf = keep
                if dropped > 0:
                    self.dropped_total += dropped
                    _count_dropped_registrations(dropped)
                    if not self._drop_logged:
                        self._drop_logged = True
                        logger.warning(
                            "location batcher buffer overflow: dropped %d "
                            "object-location registration(s) while the "
                            "conductor was unreachable (buffer cap %d); "
                            "counting further drops in the "
                            "location_registrations_dropped metric",
                            dropped, self._MAX_BUFFER)
                self._event.set()

    def flush(self) -> None:
        """Synchronous drain (shutdown; tests)."""
        with self._lock:
            batch, self._buf = self._buf, []
        if batch:
            try:
                self._send(batch)
            except Exception:
                pass

    def stop(self) -> None:
        self._stopped = True
        self._event.set()
        self.flush()


class ObjectPlane:
    def __init__(self, store: object_client.ShmClient, node_id: bytes,
                 conductor_address: str,
                 daemon_address: Optional[str] = None):
        from ray_tpu import config
        self.store = store
        self.node_id = node_id
        self.conductor = get_client(
            conductor_address,
            reconnect_s=config.get("gcs_rpc_reconnect_s"))
        # Local daemon (when co-resident with one): the put-side
        # backpressure target — an ST_OOM create asks it to
        # spill-then-admit instead of failing the put.
        self.daemon_address = daemon_address
        # Optional callable key -> bool set by the task runtime: True
        # when the object is lineage-recoverable (feeds the
        # restore-vs-reconstruct cost choice for spilled objects).
        self.lineage_hint = None
        self._restored_objects = 0
        self._restored_bytes = 0
        self._pull_locks: Dict[bytes, threading.Lock] = {}
        self._pull_guard = threading.Lock()
        self._pull_budget = _ByteBudget(
            config.get("max_concurrent_pull_bytes"))
        self._loc_batcher = _LocationBatcher(self.conductor, node_id)
        self._inline = _InlineCache(
            int(config.get("inline_cache_max_bytes")))
        self._inline_gen = None
        self._inline_max_v = 64 << 10

    def _inline_max(self) -> int:
        """The single small-object threshold (max_inline_object_bytes),
        cached against the config generation — this sits on every put/get.
        """
        from ray_tpu import config
        if self._inline_gen != config.generation:
            self._inline_max_v = int(config.get("max_inline_object_bytes"))
            self._inline_gen = config.generation
        return self._inline_max_v

    # -- write ----------------------------------------------------------
    def put_value(self, oid: ObjectID, value: Any) -> int:
        """Serialize + store, copying large buffers once (straight into the
        shm mapping). Contained ObjectRefs are registered as children so
        the stored object keeps them alive (reference_count.h nested refs).
        """
        total, segments, refs = serialization.serialize_segments(value)
        return self.put_segments(oid, total, segments, refs)

    def put_segments(self, oid: ObjectID, total: int, segments: list,
                     refs: list) -> int:
        """Store an already-serialized value (the worker return path
        serializes once to decide inline-vs-store and lands here for the
        store-backed half)."""
        key = self._key(oid)
        if refs:
            from ray_tpu.core import refs as _refs_mod
            t = _refs_mod._tracker
            if t is not None:
                t.add_children(key, [store_key(r.id.binary()) for r in refs])
        try:
            if total <= self._inline_max():
                # One store round trip (vs create+seal, plus the client's
                # open/pwrite/close) — task results are overwhelmingly
                # this shape.
                blob = segments[0] if len(segments) == 1 else \
                    b"".join(bytes(memoryview(s).cast("B"))
                             for s in segments)
                self._with_put_backpressure(
                    total, lambda: self.store.put_inline(key, blob))
            else:
                def _create():
                    w = self.store.create_writer(key, total)
                    try:
                        off = 0
                        for seg in segments:
                            off += w.write_at(off, seg)
                    finally:
                        w.close()
                    self.store.seal(key)
                self._with_put_backpressure(total, _create)
        except object_client.ObjectStoreError as e:
            if "already exists" not in str(e):
                raise
        device = ""
        if segments and serialization.is_array_blob(segments[0]):
            hdr = serialization.array_header(segments[0])
            device = hdr["device"] if hdr else ""
            _events.emit("object.array.put", key.hex(), value=float(total))
        self._loc_batcher.add(key, device=device)
        return total

    def put_blob(self, oid: ObjectID, blob: bytes) -> int:
        key = self._key(oid)
        try:
            if len(blob) <= self._inline_max():
                # Same one-round-trip create+copy+seal fast path as
                # put_value (raw puts and spill restores are often small).
                self._with_put_backpressure(
                    len(blob), lambda: self.store.put_inline(key, blob))
            else:
                def _create():
                    w = self.store.create_writer(key, len(blob))
                    try:
                        w.write_at(0, blob)
                    finally:
                        w.close()
                    self.store.seal(key)
                self._with_put_backpressure(len(blob), _create)
        except object_client.ObjectStoreError as e:
            if "already exists" not in str(e):
                raise
        self._loc_batcher.add(key)
        return len(blob)

    def _with_put_backpressure(self, nbytes: int, attempt):
        """Run a store-create closure with spill-then-admit backpressure:
        a create that hits ST_OOM asks the co-resident daemon to spill
        cold objects and retries within object_spill_put_timeout_s,
        instead of failing a put the store could admit after spilling
        (the create-retry half of local_object_manager.h's role)."""
        from ray_tpu import config
        try:
            return attempt()
        except object_client.ObjectStoreFullError:
            window = float(config.get("object_spill_put_timeout_s"))
            if window <= 0 or not self.daemon_address:
                raise
        deadline = time.monotonic() + window
        _events.emit("object.put.backpressure", value=float(nbytes))
        while True:
            freed = self._request_spill(nbytes)
            try:
                return attempt()
            except object_client.ObjectStoreFullError:
                if time.monotonic() >= deadline:
                    raise
                if not freed:
                    # Nothing spillable right now (everything pinned or
                    # below threshold granularity): wait for refs to drop.
                    time.sleep(0.05)

    def _request_spill(self, nbytes: int) -> int:
        """Ask the local daemon to spill at least nbytes now. Returns
        bytes actually freed (0 on any failure — caller backs off)."""
        try:
            resp = get_client(self.daemon_address).call(
                "spill_request", want_bytes=int(nbytes))
            return int(resp.get("freed", 0))
        except Exception:
            return 0

    def put_blobs_inline(self, jobs) -> None:
        """Batched seal of small blobs: one pipelined store burst for the
        whole batch (``jobs``: list of (ObjectID, blob), each blob at most
        the inline cap — the lazy sealer's coalesced backlog)."""
        keyed = [(self._key(oid), blob) for oid, blob in jobs]
        self.store.put_inline_batch(keyed)
        for key, _ in keyed:
            self._loc_batcher.add(key)

    # -- reply-carried inline results -----------------------------------
    def add_pending(self, keys) -> None:
        """Register return keys whose values may arrive in the push reply;
        getters park on the reply instead of polling the store."""
        self._inline.add_pending(keys)

    def is_pending(self, key: bytes) -> bool:
        return self._inline.is_pending(key)

    def wait_inline(self, key: bytes, timeout: float) -> bool:
        """True once ``key`` is not (or no longer) reply-pending."""
        return self._inline.wait_resolved(key, timeout)

    def seed_inline(self, key: bytes, blob: bytes,
                    producer_node: Optional[bytes] = None) -> None:
        """Cache a reply-carried result and wake parked getters. The
        producer's node is pre-registered in the object directory so
        remote consumers discover the lazily-sealed copy (or get a
        deterministic lost verdict if the producer dies before sealing)."""
        self._inline.seed(key, blob)
        if producer_node:
            self._loc_batcher.add(key, producer_node)

    def resolve_pending(self, key: bytes) -> None:
        self._inline.resolve(key)

    def inline_blob(self, key: bytes) -> Optional[bytes]:
        return self._inline.get(key)

    def drop_inline(self, key: bytes) -> None:
        self._inline.drop(key)

    def add_remote_location(self, key: bytes, node_id: bytes) -> None:
        self._loc_batcher.add(key, node_id)

    # -- read -----------------------------------------------------------
    def _key(self, oid: ObjectID) -> bytes:
        # shmstored keys are 16 bytes; ObjectIDs are 20 (task id + index).
        return store_key(oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        return self.contains_key(self._key(oid))

    def contains_key(self, key: bytes) -> bool:
        if self._inline.has(key):
            return True
        try:
            return self.store.contains(key)
        except (BrokenPipeError, ConnectionError, OSError):
            # The store daemon is gone (runtime shutting down, or a chaos
            # test killed it): "not present locally" is the right answer —
            # readers fall back to the object directory / recovery.
            return False

    def contains_batch(self, oids: List[ObjectID]) -> List[bool]:
        """Readiness of many refs in ONE store round trip (the wait() fast
        path), OR-ed with the inline cache (a reply-carried result is
        gettable before its lazy seal); falls back per-ref against a
        daemon that predates the op."""
        keys = [self._key(o) for o in oids]
        try:
            present = self.store.contains_batch(keys)
        except (object_client.ObjectStoreError, BrokenPipeError,
                ConnectionError, OSError):
            present = [False] * len(keys)
            for i, k in enumerate(keys):
                try:
                    present[i] = self.store.contains(k)
                except (BrokenPipeError, ConnectionError, OSError):
                    pass
        return [p or self._inline.has(k) for p, k in zip(present, keys)]

    def get_values_local_inline(self, oids: List[ObjectID]) -> List[Any]:
        """Batch fast path for ray_tpu.get() over many refs: the inline
        cache resolves reply-carried results with no store traffic, then
        ONE store round trip resolves every LOCAL sealed small object;
        misses come back as the MISS sentinel (a stored value may
        legitimately be None) and take the per-object path (remote /
        large / unsealed)."""
        keys = [self._key(o) for o in oids]
        out: List[Any] = [MISS] * len(oids)
        need: List[int] = []
        for i, k in enumerate(keys):
            blob = self._inline.get(k)
            if blob is not None:
                out[i] = serialization.deserialize(memoryview(blob))
            else:
                need.append(i)
        if _events.enabled():
            hits = len(keys) - len(need)
            if hits:
                _events.emit("inline.hit", value=float(hits))
            if need:
                _events.emit("inline.miss", value=float(len(need)))
        if need:
            blobs = self.store.get_inline_batch(
                [keys[i] for i in need], max_bytes=self._inline_max())
            for i, b in zip(need, blobs):
                if b is not None:
                    out[i] = serialization.deserialize(memoryview(b))
        return out

    def get_value(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        key = self._key(oid)
        # Reply-carried result still (or only) in the inline cache: zero
        # store/conductor round trips.
        blob = self._inline.get(key)
        if blob is not None:
            _events.emit("inline.hit")
            return serialization.deserialize(memoryview(blob))
        _events.emit("inline.miss")
        # Small sealed LOCAL objects come back inline in ONE store round
        # trip (no get+release pair, no mmap) — the dominant pattern when
        # ray_tpu.get() collects many small task results.
        data = self.store.get_inline(key, max_bytes=self._inline_max())
        if data is not None:
            return serialization.deserialize(memoryview(data))
        view = self.get_view(oid, timeout=timeout)
        value = serialization.deserialize(view)
        # Buffer-backed values (numpy arrays) stay zero-copy views over the
        # shm mapping; the PINNED ref (get_view -> get_pinned) keeps the
        # object alive in the store until those views are GC'd, so the
        # daemon can never recycle pages under a live array.
        return value

    def get_view(self, oid: ObjectID,
                 timeout: Optional[float] = None) -> memoryview:
        """Zero-copy view, pinned: the store ref drops when the view (and
        every value deserialized over it) is garbage collected."""
        key = self._key(oid)
        # Fast path: local.
        view = self._get_pinned_tolerant(key)
        if view is not None:
            return view
        deadline = None if timeout is None else time.monotonic() + timeout
        # Loss detection: once a locate round ADVERTISED holders and every
        # pull from them failed definitively (holder unreachable or it
        # denied having the object), a later round with no live holders
        # means the object is gone, not merely not-yet-computed — raise
        # ObjectLostError so callers engage lineage recovery (or surface
        # the loss) instead of spinning until (or past) their deadline.
        holders_failed = False
        while True:
            remaining = 2.0 if deadline is None else deadline - time.monotonic()
            if remaining <= 0:
                if holders_failed:
                    raise ObjectLostError(
                        oid.hex(), "all advertised holders unreachable")
                raise GetTimeoutError(
                    f"timed out waiting for object {oid.hex()}")
            loc = self.conductor.call("locate_object", oid=key,
                                      timeout=min(remaining, 2.0))
            view = self._get_pinned_tolerant(key)
            if view is not None:
                return view
            nodes = [n for n in loc["nodes"]
                     if n["node_id"] != self.node_id]
            if loc.get("lost") and not nodes and not loc.get("spilled"):
                # The directory itself declared the object lost: every
                # registered copy died with its node (or was removed by a
                # failed-pull report) and there is no spill. Deterministic
                # — no need to wait for our own pulls to fail.
                raise ObjectLostError(
                    oid.hex(), "directory reports all object copies lost "
                    "(holder nodes died, no spill copy)")
            if nodes:
                # ONE striped/windowed pull covers every advertised holder
                # (probe, pick sources, fail over internally).
                outcome = self._pull_from(key, nodes)
                if outcome == "ok":
                    view = self._get_pinned_tolerant(key)
                    if view is not None:
                        return view
                elif outcome in ("missing", "unreachable"):
                    # Every probed holder failed definitively.
                    holders_failed = True
            if loc.get("spilled") and (not nodes or holders_failed):
                # Third source tier: no live shm copy is reachable but a
                # durable spill copy exists — restore it instead of
                # declaring the object lost. When lineage could ALSO
                # recover it, a cost heuristic may prefer re-execution
                # (Ownership-paper recovery-cost argument).
                size = int(loc.get("spilled_size") or 0)
                if self._should_reconstruct(oid, size):
                    raise ObjectLostError(
                        oid.hex(), "spill copy bypassed: lineage "
                        "reconstruction preferred by cost heuristic")
                if self._restore_spilled(key, loc["spilled"], size):
                    view = self._get_pinned_tolerant(key)
                    if view is not None:
                        return view
                else:
                    # Unreadable spill URL (a node-local spill dir died
                    # with its node): scrub the directory entry so the
                    # next locate round sees lost / reconstructs.
                    try:
                        self.conductor.call("remove_spilled", oid=key,
                                            url=loc["spilled"])
                    except Exception:
                        pass
                    holders_failed = True
            elif not nodes and not loc.get("spilled") and holders_failed:
                # Every holder we were pointed at failed AND the directory
                # (now scrubbed of them by the pull's removal reports)
                # lists none: fully lost. A reconstruction that re-creates
                # the object registers a new location and wakes the locate
                # long-poll above before this branch can trigger.
                raise ObjectLostError(
                    oid.hex(), "object has no live holders and no spill "
                    "copy (all advertised replicas failed)")
            # No location known yet (still being computed) -> loop.

    def _get_pinned_tolerant(self, key: bytes) -> Optional[memoryview]:
        """get_pinned that treats a store-side error as not-yet-available.
        Under heavy overcommit a native spill-restore can fail transiently
        (every resident byte pinned by readers): the getter should retry
        within its own deadline — refs drop and space frees — rather than
        surface a hard store error for an object that still exists."""
        try:
            return self.store.get_pinned(key, timeout=0.0)
        except object_client.ObjectStoreError:
            return None

    def _should_reconstruct(self, oid: ObjectID, size: int) -> bool:
        """Restore-vs-reconstruct cost choice for a spilled object:
        restore costs ~size bytes of backend I/O, re-execution costs one
        task. With the default knob (0) restore always wins; when
        object_spill_reconstruct_min_bytes is set, objects at least that
        large prefer lineage re-execution — IF the runtime actually holds
        lineage for the object (the lineage_hint callback)."""
        from ray_tpu import config
        floor = int(config.get("object_spill_reconstruct_min_bytes"))
        if floor <= 0 or (size and size < floor):
            return False
        hint = self.lineage_hint
        try:
            return bool(hint is not None and hint(oid))
        except Exception:
            return False

    def _restore_spilled(self, key: bytes, url: str, size: int) -> bool:
        """Restore one spilled object into local shm from its URL (the
        third tier of get_view). Admitted through the same pull byte
        budget as remote pulls; single-flight per object."""
        from ray_tpu.cluster import spill as _spill
        with self._pull_guard:
            lock = self._pull_locks.setdefault(key, threading.Lock())
        with lock:
            if self.store.contains(key):
                return True
            admitted = max(size, 1)
            self._pull_budget.acquire(admitted)
            t0 = time.monotonic()
            try:
                fault_plane.fire("object.spill.restore", oid=key, url=url)
                data = _spill.read_url(url)
                try:
                    if len(data) <= self._inline_max():
                        self._with_put_backpressure(
                            len(data),
                            lambda: self.store.put_inline(key, data))
                    else:
                        def _create():
                            w = self.store.create_writer(key, len(data))
                            try:
                                w.write_at(0, data)
                            finally:
                                w.close()
                            self.store.seal(key)
                        self._with_put_backpressure(len(data), _create)
                except object_client.ObjectStoreError as e:
                    if "already exists" not in str(e):
                        raise
            except Exception:
                self._discard_partial(key)
                return False
            finally:
                self._pull_budget.release(admitted)
            self._restored_objects += 1
            self._restored_bytes += len(data)
            self._loc_batcher.add(key)
            _events.emit("object.spill.restore", key.hex(),
                         value=float(len(data)),
                         attrs={"secs": time.monotonic() - t0})
            return True

    def _pull(self, key: bytes, remote_addr: str,
              holder_id: Optional[bytes] = None) -> str:
        """Single-source pull (compat shim over _pull_from): one holder,
        no striping. Benchmarks use it to measure the raw per-link path."""
        return self._pull_from(
            key, [{"address": remote_addr, "node_id": holder_id}])

    def _pull_from(self, key: bytes, nodes: List[dict]) -> str:
        """Windowed, multi-source chunked pull of one object into local shm
        (pull_manager.h chunk-window + location-striping roles).

        ``nodes`` are the advertised non-local holders ({"node_id",
        "address"}). Single-flight per object: concurrent getters wait on
        the same pull. Probes every holder concurrently (object_info
        doubles as liveness check and load report), stripes the chunk
        ranges across up to object_pull_max_sources of the least-loaded
        holders for large objects, keeps object_pull_window fetch_chunk
        futures pipelined, writes completions out of order, and reassigns
        a failed holder's remaining chunks to the survivors.

        Returns "ok", or a failure class: "missing" (holders deny having
        it), "unreachable" (holder connections dead), "error"
        (local/other). missing/unreachable holders are reported to the
        directory (remove_object_location) so locate rounds — ours and
        every other node's — stop retrying replicas that cannot serve.
        """
        with self._pull_guard:
            lock = self._pull_locks.setdefault(key, threading.Lock())
        with lock:
            if self.store.contains(key):
                return "ok"
            _events.emit("pull.window", key.hex(), value=float(len(nodes)))
            watch = _events.watch_begin("pull", key.hex())
            t_pull = time.monotonic()
            admitted = 0
            created = False
            try:
                fault_plane.fire("object.pull", oid=key)
                holders, size, any_unreachable = self._probe_holders(
                    key, nodes)
                if not holders:
                    return "unreachable" if any_unreachable else "missing"
                sources = self._select_sources(holders, size)
                self._pull_budget.acquire(size)
                admitted = size
                # Backpressured create: a pull into a full store spills
                # cold locals to make room instead of erroring the get.
                w = self._with_put_backpressure(
                    size, lambda: self.store.create_writer(key, size))
                created = True
                try:
                    if self._shm_direct(key, w, size, holders):
                        outcome = "ok"
                    else:
                        outcome = self._run_transfer(key, w, size, sources)
                finally:
                    w.close()
                if outcome != "ok":
                    self._discard_partial(key)
                    return outcome
                self.store.seal(key)
            except object_client.ObjectStoreError as e:
                if "already exists" in str(e):
                    return "ok"
                if created:
                    self._discard_partial(key)
                raise
            except (ConnectionError, ConnectionLost, OSError, RpcError):
                if created:
                    self._discard_partial(key)
                return "unreachable"
            except Exception:
                if created:
                    self._discard_partial(key)
                return "error"
            finally:
                if admitted:
                    self._pull_budget.release(admitted)
                _events.watch_end(watch)
            self._loc_batcher.add(key)
            _events.emit("pull.done", key.hex(),
                         value=time.monotonic() - t_pull)
            return "ok"

    def _probe_holders(self, key: bytes, nodes: List[dict]):
        """Concurrent object_info probe of every advertised holder ->
        ([(node, client, transfer load)], size, any_unreachable). Holders
        that deny the object or whose connection is dead are reported to
        the directory."""
        probes = []
        for node in nodes:
            cli = get_client(node["address"])
            try:
                # _retry=True: one immediate fresh-channel resend if the
                # cached pipelined channel went stale (same at-least-once
                # contract as call(); object_info is a pure read).
                fut = cli.call_async("object_info", oid=key, _retry=True)
            except Exception:  # noqa: BLE001 - connect failed
                fut = None
            probes.append((node, cli, fut))
        holders = []
        size = 0
        any_unreachable = False
        for node, cli, fut in probes:
            try:
                if fut is None:
                    raise ConnectionLost("connect failed")
                info = fut.result(timeout=10.0)
            except (ConnectionError, ConnectionLost, OSError, RpcError,
                    _FutureTimeout):
                any_unreachable = True
                self._drop_location(key, node["node_id"])
                continue
            if not info.get("found"):
                self._drop_location(key, node["node_id"])
                continue
            size = info["size"]
            holders.append((node, cli, info.get("transfers", 0),
                            info.get("shm_path")))
        return holders, size, any_unreachable

    def _select_sources(self, holders: list, size: int) -> list:
        """Least-loaded holder choice with random tie-break (load-spread:
        a broadcast wave fans out over fresh copies instead of piling on
        the origin); large objects take several sources for striping."""
        from ray_tpu import config
        random.shuffle(holders)
        holders.sort(key=lambda h: h[2])  # stable: ties stay shuffled
        if size >= int(config.get("object_stripe_min_bytes")) \
                and len(holders) > 1:
            return holders[:max(1, int(config.get(
                "object_pull_max_sources")))]
        return holders[:1]

    def _shm_direct(self, key: bytes, w: object_client.ShmWriter,
                    size: int, holders: list) -> bool:
        """Same-host fast path: when a holder daemon shares this machine,
        its segment file is visible in our /dev/shm — pin it remotely,
        then copy mapping-to-mapping (one memcpy at memory bandwidth,
        ~4x the TCP chunk path on loopback). The pin keeps the segment
        from being deleted or recycled under the copy; any failure falls
        back to the chunked transfer. Parity: plasma's same-node
        zero-copy sharing (Ray never streams between co-located object
        managers)."""
        from ray_tpu import config
        if size == 0 or not config.get("object_pull_shm_direct"):
            return False
        for node, cli, _load, path in holders:
            if not path:
                continue
            try:
                if os.stat(path).st_size != size:
                    continue  # another host's coincidental segment name
            except OSError:
                continue
            pinned = False
            fd = -1
            try:
                if not cli.call("pin_object", oid=key).get("ok"):
                    continue
                pinned = True
                fd = os.open(path, os.O_RDONLY)
                if os.fstat(fd).st_size != size:
                    continue
                mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
                mv = memoryview(mm)
                try:
                    w.write_at(0, mv)
                finally:
                    mv.release()
                    mm.close()
                _events.emit("pull.shm_direct", key.hex(),
                             value=float(size),
                             attrs={"holder": node["address"]})
                return True
            except Exception:  # noqa: BLE001 - fall back to chunked pull
                continue
            finally:
                if fd >= 0:
                    os.close(fd)
                if pinned:
                    try:
                        cli.call("unpin_object", oid=key)
                    except Exception:
                        pass
        return False

    def _run_transfer(self, key: bytes, w: object_client.ShmWriter,
                      size: int, sources: list) -> str:
        """Windowed multi-source chunk loop -> "ok" | failure class.

        Chunk offsets are striped round-robin across the sources; up to
        object_pull_window fetch_chunk futures stay in flight on the
        pipelined channels and completions land in the writer OUT OF
        ORDER (write_at takes any offset). When a source fails its queued
        chunks re-stripe over the survivors; with no survivors the pull
        fails with the strongest failure class seen."""
        from ray_tpu import config
        if size == 0:
            return "ok"
        ring = _events.enabled()
        key_hex = key.hex()
        chunk_bytes = max(1, int(config.get("object_transfer_chunk_bytes")))
        window = max(1, int(config.get("object_pull_window")))
        live = {i: src for i, src in enumerate(sources)}
        pending: Dict[int, deque] = {i: deque() for i in live}
        for j, off in enumerate(range(0, size, chunk_bytes)):
            pending[j % len(sources)].append(off)
        inflight: Dict[Any, Tuple[int, int]] = {}  # future -> (src, offset)
        remaining = sum(len(q) for q in pending.values())
        any_unreachable = any_missing = False

        def _kill_source(i: int, exc: Optional[BaseException]) -> None:
            nonlocal any_unreachable, any_missing
            node, _cli, _load, _path = live.pop(i)
            if isinstance(exc, (ConnectionError, ConnectionLost, OSError,
                                RpcError, _FutureTimeout)):
                any_unreachable = True
            elif isinstance(exc, KeyError):
                any_missing = True  # holder dropped the object mid-pull
            self._drop_location(key, node["node_id"])
            orphans = pending.pop(i, deque())
            _events.emit("pull.failover", key.hex(),
                         value=float(len(orphans)),
                         attrs={"holder": node["address"]})
            if live:
                order = list(live)
                for j, off in enumerate(orphans):
                    pending[order[j % len(order)]].append(off)

        def _issue_one() -> bool:
            # Round-robin over live sources with queued work; False when
            # nothing is issuable (window fills stop at remaining work).
            for i in sorted(live, key=lambda i: len(pending[i]),
                            reverse=True):
                if not pending[i]:
                    continue
                off = pending[i].popleft()
                node, cli, _load, _path = live[i]
                try:
                    fault_plane.fire("object.pull.chunk", oid=key,
                                     offset=off)
                    act = fault_plane.fire(
                        "object.pull.window", oid=key, offset=off,
                        holder=node["address"])
                    if act == "sever":
                        cli.sever_pipe()
                    fut = cli.call_async(
                        "fetch_chunk", oid=key, offset=off,
                        size=min(chunk_bytes, size - off))
                except BaseException as e:  # noqa: BLE001
                    pending[i].appendleft(off)
                    _kill_source(i, e)
                    return bool(live)
                inflight[fut] = (i, off)
                return True
            return False

        while remaining:
            while len(inflight) < window and _issue_one():
                pass
            if not inflight:
                # Sources exhausted with chunks still owed.
                break
            done, _ = _futures_wait(inflight, timeout=30.0,
                                    return_when=FIRST_COMPLETED)
            if not done:
                return "error"  # stalled transfer: no completion in 30s
            for fut in done:
                i, off = inflight.pop(fut)
                try:
                    chunk = fut.result()
                except BaseException as e:  # noqa: BLE001
                    if i in live:
                        _kill_source(i, e)
                    if live:
                        order = sorted(live, key=lambda k: len(pending[k]))
                        pending[order[0]].append(off)
                    continue
                w.write_at(off, chunk)
                if ring:
                    _events.emit("pull.chunk", key_hex,
                                 value=float(len(chunk)))
                remaining -= 1
        if remaining:
            if any_unreachable:
                return "unreachable"
            return "missing" if any_missing else "error"
        return "ok"

    def _discard_partial(self, key: bytes) -> None:
        # A failed pull must not leave a CREATED (unsealed) object behind:
        # the next attempt's create would report "already exists" (mapped
        # to "ok") while readers spin on an object nobody is filling.
        try:
            self.store.delete(key)
        except Exception:
            pass

    def _drop_location(self, key: bytes, holder_id: Optional[bytes]) -> None:
        if holder_id is None:
            return
        try:
            self.conductor.call("remove_object_location", oid=key,
                                node_id=holder_id)
        except Exception:
            pass  # directory unreachable; the next locate retries anyway

    def free(self, oid: ObjectID) -> None:
        self.conductor.call("free_object", oid=self._key(oid))

    # -- collective-backed broadcast (r16) -------------------------------
    def broadcast_object(self, oid: ObjectID, members: List[dict]) -> dict:
        """Spread one local object to ``members`` (daemon descriptors
        {"node_id", "address"}) via a tree of coordinated pulls — the
        gloo-style CPU-host collective over the pipelined RPC layer
        (on-TPU meshes broadcast in-program via collectives.broadcast_from
        and never hit this path). Each round every holder serves up to
        ``array_bcast_fanout`` new members, so aggregate bandwidth scales
        with the number of fresh copies instead of serializing N pulls
        through the origin's NIC (reference: collective-backed GPU object
        broadcast, python/ray/util/collective).

        A member whose tree leg fails (injected sever, daemon hiccup) is
        re-striped onto the classic directory-driven pull path — zero
        loss, degraded speed. Returns
        {"ok": [...], "fallback": [...], "failed": [...], "skipped": bool}
        of member node_ids.
        """
        from ray_tpu import config
        from ray_tpu.parallel import collectives

        key = self._key(oid)
        members = [m for m in members if m["node_id"] != self.node_id]
        result = {"ok": [], "fallback": [], "failed": [], "skipped": False}
        if not members:
            return result
        view = self._get_pinned_tolerant(key)
        if view is None:
            raise ObjectLostError(
                oid.hex(), "broadcast root does not hold the object")
        size = view.nbytes
        del view
        # Make sure the directory already knows the root's copy before any
        # member's pull (or its classic fallback) does a locate round.
        self._loc_batcher.flush()
        if size < int(config.get("array_bcast_min_bytes")) \
                or not self.daemon_address:
            # Too small for tree coordination to beat N direct pulls (or
            # no co-resident daemon to serve as rank-0 source): classic.
            result["skipped"] = True
            _events.emit("object.bcast.fallback", key.hex(),
                         value=float(len(members)))
            for m in members:
                if self._bcast_member_pull(key, m, None):
                    result["ok"].append(m["node_id"])
                else:
                    result["failed"].append(m["node_id"])
            return result
        leg_timeout = float(config.get("array_bcast_leg_timeout_s"))
        fanout = int(config.get("array_bcast_fanout"))
        # Rank 0 is the root (this plane's co-resident daemon shares its
        # store, so it can serve the object); ranks 1..n are the members.
        ranks = [{"node_id": self.node_id, "address": self.daemon_address}]
        ranks.extend(members)
        t0 = time.monotonic()
        reached: Dict[int, bool] = {0: True}
        fallback: List[int] = []
        for legs in collectives.broadcast_rounds(len(ranks), fanout=fanout):
            threads = []
            outcomes: Dict[int, bool] = {}

            def _leg(src: int, dst: int) -> None:
                ok = False
                try:
                    cli = get_client(ranks[dst]["address"])
                    # Legs ride the pipelined channel (call_async, single
                    # attempt): a severed channel fails the future FAST
                    # and the member re-stripes, instead of the pooled
                    # call path's transparent reconnect masking the cut.
                    fut = cli.call_async("pull_object", oid=key,
                                         sources=[ranks[src]])
                    act = fault_plane.fire(
                        "object.collective.bcast", oid=key,
                        src=ranks[src]["address"],
                        dst=ranks[dst]["address"])
                    if act == "sever":
                        cli.sever_pipe()
                    resp = fut.result(timeout=leg_timeout)
                    ok = bool(resp.get("ok"))
                except Exception:  # noqa: BLE001 - leg re-stripes below
                    ok = False
                outcomes[dst] = ok
                if ok:
                    _events.emit("object.bcast.leg", key.hex(),
                                 value=float(size))

            for src, dst in legs:
                if not reached.get(src):
                    # Upstream leg failed: this subtree re-stripes onto
                    # the classic path instead of pulling from a source
                    # that never got the object.
                    outcomes[dst] = False
                    continue
                t = threading.Thread(target=_leg, args=(src, dst),
                                     name="bcast-leg", daemon=True)
                threads.append(t)
                t.start()
            for t in threads:
                t.join()
            for src, dst in legs:
                if outcomes.get(dst):
                    reached[dst] = True
                else:
                    fallback.append(dst)
        for r, ok in reached.items():
            if r and ok:
                result["ok"].append(ranks[r]["node_id"])
        if fallback:
            _events.emit("object.bcast.fallback", key.hex(),
                         value=float(len(fallback)))
            for r in fallback:
                if self._bcast_member_pull(key, ranks[r], None):
                    result["fallback"].append(ranks[r]["node_id"])
                else:
                    result["failed"].append(ranks[r]["node_id"])
        _events.emit("object.bcast.done", key.hex(),
                     value=time.monotonic() - t0,
                     attrs={"members": len(members), "bytes": size,
                            "fallback": len(fallback)})
        return result

    def _bcast_member_pull(self, key: bytes, member: dict,
                           sources: Optional[list]) -> bool:
        """One member's directory-driven (classic) pull — the re-stripe
        target for failed tree legs. Its own connection may be the severed
        one, so retry once on a fresh channel before giving up."""
        from ray_tpu import config
        timeout = float(config.get("array_bcast_leg_timeout_s"))
        for _ in range(2):
            try:
                resp = get_client(member["address"]).call(
                    "pull_object", oid=key, sources=sources,
                    _timeout=timeout)
                if resp.get("ok"):
                    return True
            except Exception:  # noqa: BLE001
                continue
        return False

    # -- introspection ---------------------------------------------------
    def metrics_probe(self) -> Dict[str, float]:
        """Point-in-time gauges for the event flusher (registered via
        events.register_probe — sampled once per flush period, never on
        the put/get hot path)."""
        inline = self._inline
        with inline._cv:
            cache_entries = len(inline._blobs)
            cache_bytes = inline._nbytes
            pending = len(inline._pending)
        budget = self._pull_budget
        with budget._cv:
            pull_used = budget._used
            pull_waiters = len(budget._queue)
        with self._loc_batcher._lock:
            loc_backlog = len(self._loc_batcher._buf)
        return {
            "rt_inline_cache_entries": float(cache_entries),
            "rt_inline_cache_bytes": float(cache_bytes),
            "rt_inline_pending_returns": float(pending),
            "rt_pull_inflight_bytes": float(pull_used),
            "rt_pull_budget_waiters": float(pull_waiters),
            "rt_location_batch_backlog": float(loc_backlog),
            "rt_spill_restored_objects": float(self._restored_objects),
            "rt_spill_restored_bytes": float(self._restored_bytes),
            "rt_array_pins_live": float(serialization.live_array_pins()),
        }

    def debug_state(self) -> dict:
        """Table sizes + budgets for debug-state dumps (the ObjectManager
        / PullManager sections of raylet's debug_state.txt)."""
        inline = self._inline
        with inline._cv:
            inline_state = {
                "cache_entries": len(inline._blobs),
                "cache_bytes": inline._nbytes,
                "cache_max_bytes": inline.max_bytes,
                "pending_returns": len(inline._pending),
            }
        budget = self._pull_budget
        with budget._cv:
            pull_state = {"budget_cap": budget.cap,
                          "budget_used": budget._used,
                          "budget_waiters": len(budget._queue),
                          "locks": len(self._pull_locks)}
        with self._loc_batcher._lock:
            batcher_state = {
                "backlog": len(self._loc_batcher._buf),
                "dropped_total": self._loc_batcher.dropped_total,
            }
        return {"inline_cache": inline_state, "pulls": pull_state,
                "location_batcher": batcher_state,
                "Restored": self._restored_objects,
                "restored_bytes": self._restored_bytes}

    def stop(self) -> None:
        self._loc_batcher.stop()
