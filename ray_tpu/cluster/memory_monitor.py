"""Node memory monitor + worker-killing policy.

Role parity: src/ray/common/memory_monitor.h:52 (periodic usage sampling
against a threshold, cgroup/procfs-based) and
src/ray/raylet/worker_killing_policy.h:34 (pick a victim worker when the
node is over the threshold: prefer retriable work, then the most recently
started — the reference's group-by-retriable-then-LIFO policy).

The daemon kills the victim's worker process; the submitter observes the
dead lease/actor and retries through the normal fault-tolerance path, so
an OOM-killed retriable task re-runs instead of taking the daemon down
with it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional


def system_memory_usage_fraction() -> float:
    """Fraction of system memory in use, from /proc/meminfo (the reference
    reads the same, memory_monitor.cc GetMemoryBytes)."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total:
        return 0.0
    return 1.0 - (avail or 0) / total


def process_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class WorkerKillingPolicy:
    """Choose a victim among candidate workers (worker_killing_policy.h:34).

    Candidates are dicts: {"pid", "retriable" (bool), "started_at" (float),
    "worker": opaque}. Preference: retriable first; within a group, the
    LAST started dies first (its work is cheapest to redo)."""

    @staticmethod
    def pick(candidates: List[dict]) -> Optional[dict]:
        if not candidates:
            return None
        return sorted(
            candidates,
            key=lambda c: (not c.get("retriable", True),
                           -(c.get("started_at") or 0.0)))[0]


class MemoryMonitor:
    """Periodic sampler; fires ``on_over_threshold`` when usage crosses the
    configured fraction. ``usage_fn`` is injectable for tests."""

    def __init__(self, threshold: float,
                 on_over_threshold: Callable[[float], None],
                 usage_fn: Callable[[], float] = system_memory_usage_fraction,
                 period_s: float = 0.25):
        self.threshold = threshold
        self._cb = on_over_threshold
        self._usage_fn = usage_fn
        self._period = period_s
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stopped.wait(self._period):
            try:
                usage = self._usage_fn()
            except Exception:
                continue
            if usage >= self.threshold:
                try:
                    self._cb(usage)
                except Exception:
                    pass

    def stop(self) -> None:
        self._stopped.set()
