"""Conductor: the cluster control plane (GCS equivalent).

Role parity: src/ray/gcs/gcs_server/gcs_server.h:77 and its per-entity
managers — node membership + health checks (gcs_health_check_manager.h),
actor registration/restart FSM + actor scheduling (gcs_actor_manager.h:281,
gcs_actor_scheduler.h:111), placement groups with 2PC prepare/commit across
node daemons (gcs_placement_group_scheduler.h:265), cluster-wide KV
(gcs_kv_manager.h), the object location directory (the reference resolves
locations through object owners, ownership_based_object_directory.h; here
the directory is centralized), and a task-event store powering the state
API/timeline (gcs_task_manager.h:61).

One conductor per cluster. All state is in-memory tables behind one lock,
with condition-variable long-polls standing in for the reference's pub/sub
channels (src/ray/pubsub/publisher.h:302).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu import config
from ray_tpu.cluster import fault_plane
from ray_tpu.cluster.protocol import RpcServer, get_client
from ray_tpu.util import lockcheck

# Actor FSM states (parity: gcs_actor_manager.h:249 state diagram).
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class ActorInfo:
    def __init__(self, actor_id: bytes, spec: dict):
        self.actor_id = actor_id
        self.spec = spec              # class blob, args, opts (pickled pieces)
        self.state = PENDING_CREATION
        self.address: Optional[str] = None   # worker rpc address when ALIVE
        self.node_id: Optional[bytes] = None
        self.num_restarts = 0
        self.death_reason = ""
        self.incarnation = 0


class PlacementGroupInfo:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]],
                 strategy: str, name: str, slice_topology: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.slice_topology = slice_topology  # SLICE strategy filter (v4-8)
        self.slice_id: Optional[str] = None   # chosen slice once CREATED
        self.state = "PENDING"        # PENDING | CREATED | REMOVED
        self.bundle_nodes: List[Optional[bytes]] = [None] * len(bundles)
        self.placing = False          # a 2PC attempt is in flight
        self.retry_scheduled = False  # a retry Timer is pending


class Conductor:
    """In-memory control-plane tables + schedulers, served over RpcServer."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 health_timeout_s: Optional[float] = None,
                 persist_dir: Optional[str] = None):
        import uuid
        self._lock = lockcheck.named_lock("conductor.state")
        self._cv = threading.Condition(self._lock)
        # Epoch: fresh per conductor process. Daemons and ref trackers
        # compare it on every exchange; a change means "the conductor
        # restarted — re-advertise your volatile state" (gcs_init_data.h
        # role: durable tables reload from disk, volatile state resyncs
        # from the fleet).
        self._epoch = uuid.uuid4().hex
        self._journal = None
        self._compact_due = False
        if persist_dir is not None:
            from ray_tpu.cluster.persistence import StateJournal
            self._journal = StateJournal(
                persist_dir.rstrip("/") + "/conductor")
        self._nodes: Dict[bytes, dict] = {}          # node_id -> info
        self._kv: Dict[Tuple[str, bytes], bytes] = {}
        self._functions: Dict[str, bytes] = {}       # function_id -> blob
        self._actors: Dict[bytes, ActorInfo] = {}
        self._named_actors: Dict[Tuple[str, str], bytes] = {}
        self._object_locations: Dict[bytes, Set[bytes]] = defaultdict(set)
        # oid -> device placement string for array objects whose producer
        # was device-resident (r16): locate_object surfaces it so pullers
        # sharing the producer's mesh can prefer a device-to-device source.
        self._object_devices: Dict[bytes, str] = {}
        # oid -> (spill url, size). Survives the writing node's death —
        # that is the point: locate_object keeps advertising the URL so
        # any node restores from the durable copy instead of declaring
        # the object lost (local_object_manager.h spilled-url role).
        self._object_spilled: Dict[bytes, tuple] = {}
        # Objects whose every registered copy died with its node (and no
        # spill). Lets locate_object tell getters "lost, stop waiting"
        # instead of being indistinguishable from not-yet-computed; cleared
        # when a copy re-registers (lineage reconstruction).
        self._lost_objects: Set[bytes] = set()
        # --- distributed refcounting (reference_count.h:61, centralized;
        #     counts driven by ordered event streams from every process) ---
        self._refcounts: Dict[bytes, int] = {}
        self._ref_children: Dict[bytes, List[bytes]] = {}
        self._ref_tombstones: Set[bytes] = set()   # freed; stray seals die
        self._ref_tombstone_order: deque = deque()
        self._ref_batches_seen: Set[str] = set()   # at-least-once dedup
        self._ref_batch_order: deque = deque()
        self._free_q: deque = deque()              # (node_addr, oid) deletes
        self._spill_del_q: deque = deque()         # spill URLs to delete
        self._free_cv = threading.Condition()
        self._pgs: Dict[bytes, PlacementGroupInfo] = {}
        self._task_events: List[dict] = []
        # Flight-recorder event store (util/events.py sink; parity role:
        # GcsTaskManager's bounded task-event store). Own lock: batches
        # arrive from every process's flusher/heartbeat and must not
        # contend with the control tables.
        self._ring_lock = threading.Lock()
        self._ring_events: List[dict] = []
        self._ring_dropped = 0
        self._job_counter = 0
        self._health_timeout_s = (
            health_timeout_s if health_timeout_s is not None
            else float(config.get("health_check_timeout_s")))
        self._stopped = False
        # worker-log pubsub ring (log streaming to drivers / `job logs`).
        # Own CV: log polls must not wake on (or scan under) the global
        # control-plane lock's notify_all traffic.
        self._log_cv = threading.Condition()
        self._log_buffer: deque = deque(maxlen=20000)
        self._log_seq = 0
        # Structured cluster events (parity: src/ray/util/event.h + the
        # dashboard's cluster-events table). Bounded ring; deque append is
        # atomic so emitters may hold any other lock.
        self._events: deque = deque(maxlen=10000)
        self._event_seq = 0
        self._event_lock = threading.Lock()  # seq counter, not self._lock
        if self._journal is not None:
            self._restore()
        self.server = RpcServer(self, host=host, port=port)
        self.address = self.server.address
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="conductor-health")
        self._health_thread.start()
        self._free_thread = threading.Thread(
            target=self._free_loop, daemon=True, name="conductor-free")
        self._free_thread.start()

    # ------------------------------------------------------------------
    # Durable state (parity: gcs_table_storage.h writes, gcs_init_data.h
    # bulk load). Only control tables persist; see persistence.py.
    # ------------------------------------------------------------------
    def _log(self, kind: str, data: dict) -> None:
        """Journal one durable mutation. Caller may hold self._lock (the
        journal has its own lock and does no RPC)."""
        if self._journal is None:
            return
        # Fault points bracketing the durable write: a crash on "pre"
        # loses the mutation (clients re-drive via at-least-once RPC); a
        # crash on "post" leaves a committed-but-unacked record the
        # journal's CRC framing and dedup-by-id replay must absorb.
        fault_plane.fire("conductor.journal.append", kind=kind, stage="pre")
        try:
            if self._journal.append(kind, data):
                self._compact_due = True
        except OSError:
            pass
        fault_plane.fire("conductor.journal.append", kind=kind, stage="post")

    def _emit_event(self, severity: str, source: str, event_type: str,
                    message: str, **metadata) -> None:
        """Record one structured cluster event (event.h / dashboard
        ClusterEvents role). severity: INFO | WARNING | ERROR. Callers may
        hold self._lock; the dedicated seq lock keeps event_ids unique
        across concurrent RPC handler threads."""
        with self._event_lock:
            self._event_seq += 1
            seq = self._event_seq
        self._events.append({
            "event_id": seq,
            "timestamp": time.time(),
            "severity": severity,
            "source": source,
            "event_type": event_type,
            "message": message,
            "metadata": metadata,
        })

    def rpc_report_event(self, severity: str, source: str, event_type: str,
                         message: str, metadata: Optional[dict] = None
                         ) -> None:
        """Daemons/workers publish their events (OOM kills, job state,
        worker crash storms) into the same stream."""
        self._emit_event(severity, source, event_type, message,
                         **(metadata or {}))

    def rpc_list_events(self, limit: int = 1000,
                        source: Optional[str] = None,
                        severity: Optional[str] = None,
                        event_type: Optional[str] = None) -> List[dict]:
        out = [e for e in list(self._events)
               if (source is None or e["source"] == source)
               and (severity is None or e["severity"] == severity)
               and (event_type is None or e["event_type"] == event_type)]
        return out[-limit:]

    def _actor_record(self, a: "ActorInfo") -> dict:
        return {"actor_id": a.actor_id, "state": a.state,
                "address": a.address, "node_id": a.node_id,
                "num_restarts": a.num_restarts,
                "death_reason": a.death_reason,
                "incarnation": a.incarnation}

    def _durable_state(self) -> dict:
        """Full durable-state snapshot. Caller holds self._lock."""
        return {
            "nodes": [
                {k: v for k, v in info.items() if k != "last_heartbeat"}
                for info in self._nodes.values()],
            "actors": [
                {"spec": a.spec, **self._actor_record(a)}
                for a in self._actors.values()],
            "pgs": [
                {"pg_id": pg.pg_id, "bundles": pg.bundles,
                 "strategy": pg.strategy, "name": pg.name,
                 "slice_topology": pg.slice_topology, "state": pg.state,
                 "bundle_nodes": pg.bundle_nodes, "slice_id": pg.slice_id}
                for pg in self._pgs.values()],
            "kv": dict(self._kv),
            "functions": dict(self._functions),
            "job_counter": self._job_counter,
        }

    def _apply_snapshot(self, snap: dict) -> None:
        now = time.monotonic()
        for info in snap.get("nodes", ()):
            info = dict(info)
            info["last_heartbeat"] = now  # grace: health re-evaluates
            self._nodes[info["node_id"]] = info
        for rec in snap.get("actors", ()):
            a = ActorInfo(rec["actor_id"], rec["spec"])
            self._apply_actor_record(a, rec)
            self._actors[a.actor_id] = a
            name = a.spec["opts"].get("name") or ""
            ns = a.spec["opts"].get("namespace") or "default"
            if name and a.state != DEAD:
                self._named_actors[(ns, name)] = a.actor_id
        for rec in snap.get("pgs", ()):
            pg = PlacementGroupInfo(rec["pg_id"], rec["bundles"],
                                    rec["strategy"], rec["name"],
                                    slice_topology=rec["slice_topology"])
            pg.state = rec["state"]
            pg.bundle_nodes = list(rec["bundle_nodes"])
            pg.slice_id = rec["slice_id"]
            self._pgs[pg.pg_id] = pg
        self._kv.update(snap.get("kv", {}))
        self._functions.update(snap.get("functions", {}))
        self._job_counter = snap.get("job_counter", 0)

    @staticmethod
    def _apply_actor_record(a: "ActorInfo", rec: dict) -> None:
        a.state = rec["state"]
        a.address = rec["address"]
        a.node_id = rec["node_id"]
        a.num_restarts = rec["num_restarts"]
        a.death_reason = rec["death_reason"]
        a.incarnation = rec["incarnation"]

    def _restore(self) -> None:
        snap, records = self._journal.load()
        if snap:
            self._apply_snapshot(snap)
        for kind, data in records:
            try:
                self._replay(kind, data)
            except Exception:
                continue
        # Restored in-flight actors re-enter scheduling once nodes return.
        pending = [a.actor_id for a in self._actors.values()
                   if a.state in (PENDING_CREATION, RESTARTING)]
        for actor_id in pending:
            threading.Timer(0.5, self._schedule_actor, (actor_id,)).start()

    def _replay(self, kind: str, data: dict) -> None:
        now = time.monotonic()
        if kind == "node":
            info = dict(data)
            info["last_heartbeat"] = now
            self._nodes[info["node_id"]] = info
        elif kind == "node_dead":
            info = self._nodes.get(data["node_id"])
            if info is not None:
                info["alive"] = False
        elif kind == "actor":
            self._replay_actor(data)
        elif kind == "actors":
            for rec in data["items"]:
                self._replay_actor(rec)
        elif kind == "actor_state":
            a = self._actors.get(data["actor_id"])
            if a is not None:
                self._apply_actor_record(a, data)
                if a.state == DEAD:
                    self._drop_name(a)
        elif kind == "pg":
            pg = PlacementGroupInfo(
                data["pg_id"], data["bundles"], data["strategy"],
                data["name"], slice_topology=data["slice_topology"])
            self._pgs[pg.pg_id] = pg
        elif kind == "pg_state":
            pg = self._pgs.get(data["pg_id"])
            if pg is not None:
                pg.state = data["state"]
                pg.bundle_nodes = list(data["bundle_nodes"])
                pg.slice_id = data["slice_id"]
        elif kind == "pg_removed":
            self._pgs.pop(data["pg_id"], None)
        elif kind == "kv":
            self._kv[(data["ns"], data["key"])] = data["value"]
        elif kind == "kv_batch":
            for rec in data["items"]:
                self._kv[(rec["ns"], rec["key"])] = rec["value"]
        elif kind == "kv_del":
            self._kv.pop((data["ns"], data["key"]), None)
        elif kind == "fn":
            self._functions[data["function_id"]] = data["blob"]
        elif kind == "job":
            self._job_counter = data["counter"]

    def _replay_actor(self, data: dict) -> None:
        a = ActorInfo(data["actor_id"], data["spec"])
        self._actors[a.actor_id] = a
        name = a.spec["opts"].get("name") or ""
        ns = a.spec["opts"].get("namespace") or "default"
        if name:
            self._named_actors[(ns, name)] = a.actor_id

    def _maybe_compact(self) -> None:
        if not self._compact_due or self._journal is None or self._stopped:
            return
        self._compact_due = False
        # Capture + truncate under the conductor lock: every _log() call
        # site holds it, so no mutation can slip between the snapshot
        # capture and the journal truncation (a frame landing in that
        # window would be in neither file — silent durability loss).
        with self._lock:
            try:
                self._journal.snapshot(self._durable_state())
            except OSError:
                pass

    # ------------------------------------------------------------------
    # Node membership + resource view (parity: GcsNodeManager + RaySyncer)
    # ------------------------------------------------------------------
    def rpc_register_node(self, node_id: bytes, address: str,
                          resources: Dict[str, float], store_socket: str,
                          is_head: bool = False,
                          tpu_slice: Optional[dict] = None) -> dict:
        with self._cv:
            self._nodes[node_id] = {
                "node_id": node_id,
                "address": address,
                "resources_total": dict(resources),
                "resources_available": dict(resources),
                "store_socket": store_socket,
                "is_head": is_head,
                "tpu_slice": dict(tpu_slice) if tpu_slice else None,
                "alive": True,
                "last_heartbeat": time.monotonic(),
            }
            self._log("node", {k: v for k, v in self._nodes[node_id].items()
                               if k != "last_heartbeat"})
            self._emit_event(
                "INFO", "conductor", "NODE_ADDED",
                f"node {node_id.hex()[:8]} joined at {address}",
                node_id=node_id.hex(), address=address, is_head=is_head)
            self._cv.notify_all()
        # A new slice host may complete a gang a pending slice PG waits on.
        with self._lock:
            pending = [pg for pg in self._pgs.values()
                       if pg.state == "PENDING"]
        for pg in pending:
            self._try_place_pg(pg)
        return {"ok": True, "epoch": self._epoch}

    # ------------------------------------------------------------------
    # TPU slice view (the differentiator: ICI-contiguous gang placement;
    # the reference's nearest analog is the PG scheduler's bundle packing,
    # gcs_placement_group_scheduler.h:265, which has no topology notion)
    # ------------------------------------------------------------------
    def _slice_view(self) -> Dict[str, dict]:
        """Group live TPU nodes by slice. Caller must hold self._lock."""
        slices: Dict[str, dict] = {}
        for info in self._nodes.values():
            if not info["alive"] or not info.get("tpu_slice"):
                continue
            ts = info["tpu_slice"]
            s = slices.setdefault(ts["slice_id"], {
                "slice_id": ts["slice_id"],
                "accelerator_type": ts["accelerator_type"],
                "generation": ts["generation"],
                "num_hosts": ts["num_hosts"],
                "hosts": [],
            })
            s["hosts"].append(info)
        for s in slices.values():
            s["hosts"].sort(key=lambda n: n["tpu_slice"]["worker_id"])
            s["complete"] = len(s["hosts"]) >= s["num_hosts"]
        return slices

    def rpc_get_slices(self) -> List[dict]:
        with self._lock:
            return [{
                "slice_id": s["slice_id"],
                "accelerator_type": s["accelerator_type"],
                "generation": s["generation"],
                "num_hosts": s["num_hosts"],
                "registered_hosts": len(s["hosts"]),
                "complete": s["complete"],
                "node_ids": [n["node_id"] for n in s["hosts"]],
            } for s in self._slice_view().values()]

    def rpc_heartbeat(self, node_id: bytes,
                      resources_available: Dict[str, float],
                      pending_demand: Optional[List[Dict[str, float]]] = None,
                      events: Optional[dict] = None) -> dict:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or not info["alive"]:
                return {"ok": False, "reregister": True,
                        "epoch": self._epoch}
            info["last_heartbeat"] = time.monotonic()
            info["resources_available"] = dict(resources_available)
            info["pending_demand"] = list(pending_demand or [])
        if events:
            # Flight-recorder piggyback: the daemon rides its ring delta
            # on the heartbeat it already pays for (events.heartbeat_payload).
            self.rpc_push_ring_events(
                node_id=node_id.hex(), pid=events.get("pid", 0),
                events=events.get("events", ()),
                dropped=events.get("dropped", 0))
        return {"ok": True, "epoch": self._epoch}

    def rpc_cluster_load(self) -> dict:
        """Autoscaler input (parity: the GCS load report monitor.py reads):
        per-shape pending demand + per-node availability."""
        with self._lock:
            demand: List[Dict[str, float]] = []
            nodes = []
            for info in self._nodes.values():
                if not info["alive"]:
                    continue
                demand.extend(info.get("pending_demand", []))
                nodes.append({
                    "node_id": info["node_id"],
                    "resources_total": dict(info["resources_total"]),
                    "resources_available": dict(info["resources_available"]),
                    "is_head": info["is_head"],
                })
            # unplaceable pending placement groups are demand too
            for pg in self._pgs.values():
                if pg.state == "PENDING":
                    demand.extend(pg.bundles)
        return {"demand": demand, "nodes": nodes}

    def rpc_drain_node(self, node_id: bytes) -> dict:
        self._mark_node_dead(node_id, "drained")
        return {"ok": True}

    def rpc_get_nodes(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._nodes.values()]

    def rpc_cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = defaultdict(float)
        with self._lock:
            for info in self._nodes.values():
                if info["alive"]:
                    for k, v in info["resources_total"].items():
                        out[k] += v
        return dict(out)

    def rpc_available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = defaultdict(float)
        with self._lock:
            for info in self._nodes.values():
                if info["alive"]:
                    for k, v in info["resources_available"].items():
                        out[k] += v
        return dict(out)

    def _health_loop(self) -> None:
        while not self._stopped:
            time.sleep(self._health_timeout_s / 4)
            self._maybe_compact()
            now = time.monotonic()
            dead = []
            with self._lock:
                for nid, info in self._nodes.items():
                    if info["alive"] and (
                            now - info["last_heartbeat"] > self._health_timeout_s):
                        dead.append(nid)
            for nid in dead:
                self._mark_node_dead(nid, "health check timed out")

    def _mark_node_dead(self, node_id: bytes, reason: str) -> None:
        to_restart: List[ActorInfo] = []
        with self._cv:
            info = self._nodes.get(node_id)
            if info is None or not info["alive"]:
                return
            info["alive"] = False
            self._log("node_dead", {"node_id": node_id})
            self._emit_event(
                "WARNING", "conductor", "NODE_DEAD",
                f"node {node_id.hex()[:8]} marked dead: {reason}",
                node_id=node_id.hex(), reason=reason)
            # Drop its object locations; owners re-resolve and recover.
            for oid, locs in list(self._object_locations.items()):
                locs.discard(node_id)
                if not locs and oid not in self._object_spilled:
                    del self._object_locations[oid]
                    self._lost_objects.add(oid)
            # Actors on this node die (and maybe restart).
            for a in self._actors.values():
                if a.node_id == node_id and a.state in (ALIVE, PENDING_CREATION,
                                                        RESTARTING):
                    to_restart.append(a)
            # Placement groups lose bundles on this node -> back to PENDING.
            for pg in self._pgs.values():
                if pg.state == "CREATED" and node_id in pg.bundle_nodes:
                    pg.state = "PENDING"
                    pg.slice_id = None
                    pg.bundle_nodes = [
                        None if n == node_id else n for n in pg.bundle_nodes]
            self._cv.notify_all()
        for a in to_restart:
            self._on_actor_death(a.actor_id, f"node died: {reason}")
        # Reap the dead node's per-process metrics snapshots: the KV keys
        # are (node, pid)-scoped, so a node's death identifies exactly its
        # entries (util/metrics.py satellite — stale keys used to linger
        # forever and shadow reused pids).
        prefix = f"proc-{node_id.hex()}-".encode()
        with self._lock:
            stale = [k for (n, k) in self._kv
                     if n == "metrics" and k.startswith(prefix)]
            for k in stale:
                self._kv.pop(("metrics", k), None)
        # Re-place any PGs knocked back to PENDING.
        with self._lock:
            pending = [pg for pg in self._pgs.values() if pg.state == "PENDING"]
        for pg in pending:
            self._try_place_pg(pg)

    # ------------------------------------------------------------------
    # KV + function table (parity: gcs_kv_manager.h, gcs_function_manager.h)
    # ------------------------------------------------------------------
    def rpc_kv_put(self, ns: str, key: bytes, value: bytes,
                   overwrite: bool = True) -> bool:
        with self._cv:
            if not overwrite and (ns, key) in self._kv:
                return False
            self._kv[(ns, key)] = value
            self._log("kv", {"ns": ns, "key": key, "value": value})
            self._cv.notify_all()
        return True

    def rpc_kv_multi_put(self, items: List[tuple],
                         overwrite: bool = True) -> List[bool]:
        """Coalesced KV writes: one lock acquisition + ONE journal record
        for N (ns, key, value) triples — a wave of writes costs O(1)
        round-trips and fsyncs instead of O(N) (parity: the reference's
        InternalKVMultiSet batching)."""
        out: List[bool] = []
        logged: List[dict] = []
        with self._cv:
            for ns, key, value in items:
                if not overwrite and (ns, key) in self._kv:
                    out.append(False)
                    continue
                self._kv[(ns, key)] = value
                logged.append({"ns": ns, "key": key, "value": value})
                out.append(True)
            if logged:
                self._log("kv_batch", {"items": logged})
                self._cv.notify_all()
        return out

    def rpc_kv_get(self, ns: str, key: bytes,
                   wait_timeout: float = 0.0) -> Optional[bytes]:
        deadline = time.monotonic() + wait_timeout
        with self._cv:
            while True:
                v = self._kv.get((ns, key))
                if v is not None or wait_timeout <= 0:
                    return v
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def rpc_kv_del(self, ns: str, key: bytes) -> bool:
        with self._lock:
            self._log("kv_del", {"ns": ns, "key": key})
            return self._kv.pop((ns, key), None) is not None

    def rpc_kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for (n, k) in self._kv if n == ns and k.startswith(prefix)]

    def rpc_put_function(self, function_id: str, blob: bytes) -> None:
        with self._lock:
            self._functions[function_id] = blob
            self._log("fn", {"function_id": function_id, "blob": blob})

    def rpc_get_function(self, function_id: str) -> Optional[bytes]:
        with self._lock:
            return self._functions.get(function_id)

    # ------------------------------------------------------------------
    # Object directory (centralizes ownership_based_object_directory.h)
    # ------------------------------------------------------------------
    def rpc_add_object_location(self, oid: bytes, node_id: bytes) -> None:
        fault_plane.fire("conductor.location.add", n=1)
        with self._cv:
            if oid in self._ref_tombstones:
                # Sealed after its refcount hit zero (fire-and-forget task
                # whose return refs were dropped pre-execution): delete the
                # stray copy instead of registering a leaked location.
                info = self._nodes.get(node_id)
                if info is not None and info["alive"]:
                    self._enqueue_delete(info["address"], oid)
                return
            self._object_locations[oid].add(node_id)
            self._lost_objects.discard(oid)
            self._cv.notify_all()

    def rpc_add_object_locations(self, oids: List[bytes],
                                 node_id: bytes,
                                 devices: Optional[List[str]] = None) -> None:
        """Bulk registration: a daemon replaying its store inventory after
        a conductor epoch change (persistence.py), or a plane's batched
        per-result registrations (object_plane._LocationBatcher). Same
        tombstone semantics as the single-oid path: a copy sealed after
        its refcount hit zero is a leak — delete it at the source.
        ``devices`` (parallel to ``oids``, r16) tags array objects with
        their producer's device placement for locate_object."""
        fault_plane.fire("conductor.location.add", n=len(oids))
        with self._cv:
            info = self._nodes.get(node_id)
            addr = info["address"] if info and info["alive"] else None
            for i, oid in enumerate(oids):
                if oid in self._ref_tombstones:
                    if addr is not None:
                        self._enqueue_delete(addr, oid)
                    continue
                self._object_locations[oid].add(node_id)
                if devices and i < len(devices) and devices[i]:
                    self._object_devices[oid] = devices[i]
                self._lost_objects.discard(oid)
            self._cv.notify_all()

    def rpc_remove_object_location(self, oid: bytes, node_id: bytes) -> None:
        """A puller found the directory stale: the holder denied having the
        object or was unreachable. Dropping the entry keeps other getters
        from hammering the same dead copy; if it was the last one (and no
        spill), the object is lost and waiters are told so."""
        with self._cv:
            locs = self._object_locations.get(oid)
            if locs:
                locs.discard(node_id)
                if not locs and oid not in self._object_spilled:
                    del self._object_locations[oid]
                    self._lost_objects.add(oid)
                    self._cv.notify_all()

    def rpc_add_spilled(self, oid: bytes, url: str, size: int = 0) -> None:
        with self._cv:
            if oid in self._ref_tombstones:
                # Freed while the spill write was in flight: the spilling
                # daemon keeps the registry entry, so its own delete path
                # (rpc_delete_objects -> _drop_spilled) removes the file.
                return
            self._object_spilled[oid] = (url, int(size))
            self._lost_objects.discard(oid)
            self._cv.notify_all()

    def rpc_remove_spilled(self, oid: bytes, url: str) -> None:
        """A restorer found the spill URL unreadable (node-local spill
        dir died with its node): scrub it so locate rounds stop pointing
        getters at a dead copy. Guarded by URL so a fresh re-spill under
        the same oid is never scrubbed by a stale failure report."""
        with self._cv:
            ent = self._object_spilled.get(oid)
            if ent is None or ent[0] != url:
                return
            del self._object_spilled[oid]
            if not self._object_locations.get(oid):
                self._object_locations.pop(oid, None)
                self._lost_objects.add(oid)
            self._cv.notify_all()

    def rpc_locate_object(self, oid: bytes, timeout: float = 0.0) -> dict:
        """Resolve an object to live node addresses (+ spill url if any)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                locs = [self._nodes[n] for n in self._object_locations.get(oid, ())
                        if n in self._nodes and self._nodes[n]["alive"]]
                sp = self._object_spilled.get(oid)
                lost = not locs and not sp and oid in self._lost_objects
                if locs or sp or lost or timeout <= 0:
                    return {
                        "nodes": [{"node_id": n["node_id"],
                                   "address": n["address"]} for n in locs],
                        "spilled": sp[0] if sp else None,
                        "spilled_size": sp[1] if sp else 0,
                        "lost": lost,
                        "device": self._object_devices.get(oid, ""),
                    }
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"nodes": [], "spilled": None,
                            "spilled_size": 0, "lost": False, "device": ""}
                self._cv.wait(min(remaining, 1.0))

    def rpc_objects_exist(self, oids: List[bytes]) -> List[bool]:
        """Batched readiness probe for dependency gating (the role of the
        raylet's DependencyManager wait-before-dispatch)."""
        with self._lock:
            return [bool(self._object_locations.get(o)) or
                    o in self._object_spilled for o in oids]

    def rpc_wait_objects(self, oids: List[bytes], num_needed: int,
                         timeout: float = 0.0) -> List[bool]:
        """Event-driven ray.wait / dependency-gate backend: long-poll until
        at least ``num_needed`` of ``oids`` exist somewhere (location or
        spill), then return the full existence bitmap. Replaces client-side
        polling (parity: the reference's object-eviction/location pubsub,
        src/ray/pubsub/publisher.h:302 — waiters park on the conductor's CV
        and wake on add_object_location instead of spinning)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                exist = [bool(self._object_locations.get(o)) or
                         o in self._object_spilled for o in oids]
                if sum(exist) >= num_needed or timeout <= 0:
                    return exist
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return exist
                self._cv.wait(min(remaining, 1.0))

    # ------------------------------------------------------------------
    # Distributed refcounting (reference_count.h:61, centralized ledger)
    # ------------------------------------------------------------------
    def rpc_ref_update(self, deltas: List[tuple],
                       epoch: Optional[str] = None,
                       batch_id: Optional[str] = None) -> dict:
        """Apply an ordered batch of count events from one process.

        Each event is ``(key, +1|-1)`` or ``(parent_key, [child_keys])``
        (the parent object contains refs to the children). Order within the
        batch is program order in the sender — applying sequentially is
        what keeps handoffs race-free (see core/refcount.py docstring).

        ``epoch`` fences failover: deltas recorded against a dead
        conductor's ledger are rejected with resync=True, and the tracker
        replays its full local truth instead (refcount ledgers are
        volatile; gcs_init_data.h reloads only durable tables)."""
        if epoch is not None and epoch != self._epoch:
            return {"epoch": self._epoch, "resync": True}
        if batch_id is not None:
            with self._lock:
                if batch_id in self._ref_batches_seen:
                    return {"epoch": self._epoch}  # at-least-once dedup
                self._ref_batches_seen.add(batch_id)
                self._ref_batch_order.append(batch_id)
                while len(self._ref_batch_order) > 4096:
                    self._ref_batches_seen.discard(
                        self._ref_batch_order.popleft())
        to_free: List[bytes] = []
        with self._lock:
            stack = list(deltas)
            for key, ev in stack:
                if isinstance(ev, list):
                    if key in self._ref_tombstones:
                        continue  # parent already freed; don't pin children
                    self._ref_children.setdefault(key, []).extend(ev)
                    for child in ev:
                        self._refcounts[child] = \
                            self._refcounts.get(child, 0) + 1
                    continue
                c = self._refcounts.get(key, 0) + ev
                if c <= 0:
                    had = key in self._refcounts
                    self._refcounts.pop(key, None)
                    # Free ONLY on a tracked 1->0 transition. A -1 against
                    # an absent key (decref outliving a conductor restart)
                    # must NOT free: the matching +1 may be lost state, and
                    # other processes may still hold the object. Those
                    # objects fall back to LRU/spill reclamation.
                    if had:
                        to_free.extend(self._collect_free(key))
                else:
                    self._refcounts[key] = c
                    # A live count always overrides a stale tombstone (a
                    # revived lineage output that regained holders).
                    self._ref_tombstones.discard(key)
        if to_free:
            with self._cv:
                self._cv.notify_all()
        return {"epoch": self._epoch}

    def _collect_free(self, key: bytes) -> List[bytes]:
        """Free ``key`` and cascade to children whose counts hit zero.
        Caller holds self._lock. Returns the freed keys."""
        freed = []
        stack = [key]
        while stack:
            k = stack.pop()
            if k in self._ref_tombstones:
                continue
            self._ref_tombstones.add(k)
            self._ref_tombstone_order.append(k)
            while len(self._ref_tombstone_order) > 200_000:
                old = self._ref_tombstone_order.popleft()
                self._ref_tombstones.discard(old)
            freed.append(k)
            self._object_devices.pop(k, None)
            for n in self._object_locations.pop(k, ()):
                info = self._nodes.get(n)
                if info is not None and info["alive"]:
                    self._enqueue_delete(info["address"], k)
            sp = self._object_spilled.pop(k, None)
            if sp is not None:
                # Spill copies are refcounted like any other copy: the
                # backend file dies on the 1->0 transition (deleted off
                # the RPC path by the free loop; the spilling daemon's
                # own delete handler covers node-local dirs we can't
                # reach from here).
                self._spill_del_q.append(sp[0])
            self._lost_objects.discard(k)
            for child in self._ref_children.pop(k, ()):
                c = self._refcounts.get(child, 0) - 1
                if c <= 0:
                    self._refcounts.pop(child, None)
                    stack.append(child)
                else:
                    self._refcounts[child] = c
        return freed

    def rpc_ref_revive(self, keys: List[bytes]) -> None:
        """Clear tombstones before lineage reconstruction re-executes a
        task whose (freed) outputs are needed as dependencies again — the
        recovered copies must be allowed to register locations."""
        with self._lock:
            for k in keys:
                self._ref_tombstones.discard(k)
                # Reconstruction is in flight: stop telling getters the
                # object is unrecoverably lost (they'd give up while the
                # re-executed task is still producing the new copy).
                self._lost_objects.discard(k)

    def _enqueue_delete(self, addr: str, oid: bytes) -> None:
        with self._free_cv:
            self._free_q.append((addr, oid))
            self._free_cv.notify()

    def _free_loop(self) -> None:
        """Background deleter: store frees must not block RPC handlers.
        Deletes are grouped per node into ONE batched RPC — churn of many
        small objects must not become thousands of serial round trips."""
        while not self._stopped:
            with self._free_cv:
                while not self._free_q and not self._spill_del_q \
                        and not self._stopped:
                    self._free_cv.wait(1.0)
                batch = []
                while self._free_q:
                    batch.append(self._free_q.popleft())
                spill_urls = []
                while self._spill_del_q:
                    spill_urls.append(self._spill_del_q.popleft())
            by_addr: Dict[str, List[bytes]] = {}
            for addr, oid in batch:
                by_addr.setdefault(addr, []).append(oid)
            for addr, oids in by_addr.items():
                try:
                    get_client(addr).call("delete_objects", oids=oids)
                except Exception:
                    pass
            if spill_urls:
                from ray_tpu.cluster import spill as _spill
                for url in spill_urls:
                    try:
                        _spill.delete_url(url)
                    except Exception:
                        pass

    def rpc_free_object(self, oid: bytes) -> None:
        with self._lock:
            nodes = [self._nodes[n]["address"]
                     for n in self._object_locations.pop(oid, ())
                     if n in self._nodes and self._nodes[n]["alive"]]
            self._object_devices.pop(oid, None)
            sp = self._object_spilled.pop(oid, None)
            self._lost_objects.discard(oid)
        if sp is not None:
            with self._free_cv:
                self._spill_del_q.append(sp[0])
                self._free_cv.notify()
        for addr in nodes:
            try:
                get_client(addr).call("delete_object", oid=oid)
            except Exception:
                pass

    # ------------------------------------------------------------------
    # Actor manager + scheduler (parity: gcs_actor_manager.h:281,
    # gcs_actor_scheduler.h:111 ScheduleByRaylet mode)
    # ------------------------------------------------------------------
    def rpc_register_actor(self, actor_id: bytes, spec: dict) -> dict:
        out = self.rpc_register_actors(
            [{"actor_id": actor_id, "spec": spec}])[0]
        if out.get("error"):
            raise ValueError(out["error"])
        return out

    def rpc_register_actors(self, items: List[dict]) -> List[dict]:
        """Wave registration: one lock acquisition + ONE journal record for
        N actors (parity: Ray's async batched GCS actor registration;
        perf pointer python/ray/_private/ray_perf.py). Each item is
        {"actor_id", "spec"}; the reply aligns with the request — per-item
        "existing" (dedup/get_if_exists hit) or "error" (name collision,
        raised by the single-actor shim, reported in-band here so one bad
        name cannot fail a whole wave)."""
        results: List[Optional[dict]] = [None] * len(items)
        to_schedule: List[bytes] = []
        logged: List[dict] = []
        with self._cv:
            for i, item in enumerate(items):
                actor_id, spec = item["actor_id"], item["spec"]
                name = spec["opts"].get("name") or ""
                ns = spec["opts"].get("namespace") or "default"
                if actor_id in self._actors:
                    # At-least-once delivery (reconnecting client resent
                    # after a lost response): actor ids are caller-
                    # generated, so a duplicate IS the same creation — ack
                    # it, don't collide on the name.
                    results[i] = {"existing": None}
                    continue
                if name:
                    existing = self._named_actors.get((ns, name))
                    if existing is not None and \
                            self._actors[existing].state != DEAD:
                        if spec["opts"].get("get_if_exists"):
                            results[i] = {"existing": existing}
                        else:
                            results[i] = {
                                "existing": None,
                                "error": f"Actor name {name!r} already "
                                         f"taken in namespace {ns!r}"}
                        continue
                    self._named_actors[(ns, name)] = actor_id
                self._actors[actor_id] = ActorInfo(actor_id, spec)
                logged.append({"actor_id": actor_id, "spec": spec})
                to_schedule.append(actor_id)
                results[i] = {"existing": None}
            if logged:
                self._log("actors", {"items": logged})
                self._cv.notify_all()
        self._schedule_actors(to_schedule)
        return results

    def _pick_node_for(self, resources: Dict[str, float],
                       strategy: Any = None) -> Optional[dict]:
        """Feasibility-checked bin-pack over the live resource view.

        Parity: hybrid_scheduling_policy.h:50 — prefer the most-available
        feasible node (scored by remaining capacity) so load spreads once
        nodes fill; placement-group strategies pin to the bundle's node.
        """
        with self._lock:
            if isinstance(strategy, dict) and strategy.get("type") == "pg":
                pg = self._pgs.get(strategy["pg_id"])
                if pg is None or pg.state != "CREATED":
                    return None
                idx = strategy.get("bundle_index", 0)
                if idx == -1:
                    idx = 0
                nid = pg.bundle_nodes[idx]
                info = self._nodes.get(nid)
                return dict(info) if info and info["alive"] else None
            if isinstance(strategy, dict) and strategy.get("type") == "node":
                info = self._nodes.get(strategy["node_id"])
                if info and info["alive"]:
                    return dict(info)
                return None if not strategy.get("soft") else self._best_fit(
                    resources)
            if isinstance(strategy, dict) and strategy.get("type") == "slice":
                # Constrain the candidate set to hosts of complete slices
                # matching the requested topology, then best-fit within it.
                topo = strategy.get("topology") or ""
                candidates: List[dict] = []
                for s in self._slice_view().values():
                    if not s["complete"]:
                        continue
                    if topo and s["accelerator_type"] != topo:
                        continue
                    candidates.extend(s["hosts"])
                return self._best_fit(resources, candidates)
            return self._best_fit(resources)

    def _best_fit(self, resources: Dict[str, float],
                  candidates: Optional[List[dict]] = None) -> Optional[dict]:
        best, best_score = None, -1.0
        pool = self._nodes.values() if candidates is None else candidates
        for info in pool:
            if not info["alive"]:
                continue
            avail = info["resources_available"]
            total = info["resources_total"]
            if any(avail.get(k, 0.0) + 1e-9 < v for k, v in resources.items()
                   if v > 0):
                continue
            # Score: fraction of capacity left after placing (pack towards
            # busy-but-feasible nodes is the reference PACK flavor; we spread
            # by preferring the emptiest feasible node for throughput).
            score = sum(avail.get(k, 0.0) / max(total.get(k, 1.0), 1e-9)
                        for k in ("CPU", "TPU"))
            if score > best_score:
                best, best_score = info, score
        return dict(best) if best else None

    def _schedule_actor(self, actor_id: bytes) -> None:
        self._schedule_actors([actor_id])

    def _schedule_actors(self, actor_ids: List[bytes]) -> None:
        """Place a wave of actors: node picks happen in one pass, then the
        conductor sends ONE ``start_actors`` RPC per target daemon instead
        of one ``start_actor`` per actor (the round-5 profile pinned wave
        collapse on exactly these serialized per-actor round-trips)."""
        # Fault point: delay/raise while a wave is being placed (a raise
        # here fails the scheduling pass; pending actors re-enter via the
        # retry timers / restart FSM, which is what chaos runs verify).
        fault_plane.fire("conductor.actor.schedule", count=len(actor_ids))
        by_node: Dict[str, List[dict]] = {}
        node_of: Dict[str, bytes] = {}
        for actor_id in actor_ids:
            with self._lock:
                a = self._actors.get(actor_id)
                if a is None or a.state == DEAD:
                    continue
                spec = a.spec
            node = self._pick_node_for(
                spec["opts"].get("resources_req", {"CPU": 1.0}),
                spec["opts"].get("scheduling_strategy"))
            if node is None:
                # No feasible node now: retry when membership/resources
                # change.
                threading.Timer(0.2, self._schedule_actor,
                                args=(actor_id,)).start()
                continue
            with self._lock:
                a = self._actors.get(actor_id)
                if a is None or a.state == DEAD:
                    continue
                a.node_id = node["node_id"]
                incarnation = a.incarnation
            by_node.setdefault(node["address"], []).append(
                {"actor_id": actor_id, "spec": spec,
                 "incarnation": incarnation})
            node_of[node["address"]] = node["node_id"]
        for addr, batch in by_node.items():
            try:
                get_client(addr).call("start_actors", items=batch)
            except Exception as e:  # node unreachable -> mark dead
                self._mark_node_dead(node_of[addr], f"unreachable: {e}")

    def rpc_actor_started(self, actor_id: bytes, address: str,
                          node_id: bytes, incarnation: int) -> None:
        with self._cv:
            a = self._actors.get(actor_id)
            if a is None or a.incarnation != incarnation:
                return
            a.state = ALIVE
            a.address = address
            a.node_id = node_id
            self._log("actor_state", self._actor_record(a))
            self._cv.notify_all()

    def rpc_actor_creation_failed(self, actor_id: bytes, incarnation: int,
                                  error_blob: bytes) -> None:
        with self._cv:
            a = self._actors.get(actor_id)
            if a is None or a.incarnation != incarnation:
                return
            a.state = DEAD
            a.death_reason = "creation failed"
            a.spec["creation_error"] = error_blob
            self._drop_name(a)
            self._log("actor_state", self._actor_record(a))
            self._cv.notify_all()

    def rpc_report_actor_death(self, actor_id: bytes, reason: str,
                               incarnation: Optional[int] = None) -> None:
        self._on_actor_death(actor_id, reason, incarnation)

    def _on_actor_death(self, actor_id: bytes, reason: str,
                        incarnation: Optional[int] = None) -> None:
        """Restart FSM (parity: gcs_actor_manager.h ALIVE->RESTARTING->...).

        ``incarnation`` dedupes reports: one worker death can be observed
        both by the daemon reaper and by a failed RPC — only the first
        report for the current incarnation burns a restart.
        """
        with self._cv:
            a = self._actors.get(actor_id)
            if a is None or a.state == DEAD:
                return
            if incarnation is not None and incarnation != a.incarnation:
                return  # stale report about an already-replaced incarnation
            max_restarts = a.spec["opts"].get("max_restarts", 0)
            if max_restarts == -1 or a.num_restarts < max_restarts:
                a.num_restarts += 1
                a.incarnation += 1
                a.state = RESTARTING
                a.address = None
                self._log("actor_state", self._actor_record(a))
                self._emit_event(
                    "WARNING", "conductor", "ACTOR_RESTARTING",
                    f"actor {a.spec.get('class_name', '')} "
                    f"{actor_id.hex()[:8]} restarting "
                    f"({a.num_restarts}/{max_restarts}): {reason}",
                    actor_id=actor_id.hex(), reason=reason)
                self._cv.notify_all()
                restart = True
            else:
                a.state = DEAD
                a.death_reason = reason
                a.address = None
                self._drop_name(a)
                self._log("actor_state", self._actor_record(a))
                self._emit_event(
                    "ERROR", "conductor", "ACTOR_DEAD",
                    f"actor {a.spec.get('class_name', '')} "
                    f"{actor_id.hex()[:8]} died: {reason}",
                    actor_id=actor_id.hex(), reason=reason)
                self._cv.notify_all()
                restart = False
        if restart:
            self._schedule_actor(actor_id)

    def _drop_name(self, a: ActorInfo) -> None:
        name = a.spec["opts"].get("name") or ""
        ns = a.spec["opts"].get("namespace") or "default"
        if name and self._named_actors.get((ns, name)) == a.actor_id:
            del self._named_actors[(ns, name)]

    def rpc_kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        with self._cv:
            a = self._actors.get(actor_id)
            if a is None:
                return
            if no_restart:
                a.spec["opts"]["max_restarts"] = 0
            addr = a.address
        if addr:
            try:
                get_client(addr).call("kill_actor", actor_id=actor_id)
            except Exception:
                pass
        self._on_actor_death(actor_id, "killed via kill()")

    def rpc_get_actor_info(self, actor_id: bytes,
                           wait_alive_timeout: float = 0.0) -> dict:
        """Resolve an actor's state/address; optionally long-poll until it
        leaves PENDING/RESTARTING (parity: actor address pubsub)."""
        deadline = time.monotonic() + wait_alive_timeout
        with self._cv:
            while True:
                a = self._actors.get(actor_id)
                if a is None:
                    return {"state": "UNKNOWN"}
                if a.state in (ALIVE, DEAD) or wait_alive_timeout <= 0:
                    return self._actor_info_of(a)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._actor_info_of(a)
                self._cv.wait(min(remaining, 1.0))

    def rpc_get_actor_infos(self, actor_ids: List[bytes],
                            wait_alive_timeout: float = 0.0) -> List[dict]:
        """Batched get_actor_info: ONE long-poll covers a whole wave (the
        driver-side shared resolver multiplexes every pending actor of a
        process into this). Returns as soon as any actor newly leaves
        PENDING/RESTARTING — the caller unblocks what resolved and re-polls
        for the rest — or at the timeout. Unregistered ids report UNKNOWN
        but keep the poll alive: with driver-side registration coalescing a
        wave member may be an in-flight register away."""
        deadline = time.monotonic() + wait_alive_timeout

        def snapshot():
            infos, resolved = [], 0
            for aid in actor_ids:
                a = self._actors.get(aid)
                if a is None:
                    infos.append({"state": "UNKNOWN"})
                else:
                    infos.append(self._actor_info_of(a))
                    if a.state in (ALIVE, DEAD):
                        resolved += 1
            return infos, resolved

        with self._cv:
            infos, baseline = snapshot()
            if wait_alive_timeout <= 0 or baseline == len(actor_ids):
                return infos
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return infos
                self._cv.wait(min(remaining, 1.0))
                infos, resolved = snapshot()
                if resolved > baseline or resolved == len(actor_ids):
                    return infos

    @staticmethod
    def _actor_info_of(a: "ActorInfo") -> dict:
        return {"state": a.state, "address": a.address,
                "node_id": a.node_id,
                "incarnation": a.incarnation,
                "death_reason": a.death_reason,
                "creation_error": a.spec.get("creation_error"),
                "class_name": a.spec.get("class_name", ""),
                "methods": a.spec.get("methods"),
                "is_async": a.spec.get("is_async", False)}

    def rpc_get_named_actor(self, name: str, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._named_actors.get((namespace or "default", name))

    def rpc_list_actors(self) -> List[dict]:
        with self._lock:
            return [{"actor_id": a.actor_id.hex(), "state": a.state,
                     "class_name": a.spec.get("class_name", ""),
                     "name": a.spec["opts"].get("name", ""),
                     "node_id": a.node_id.hex() if a.node_id else None,
                     "num_restarts": a.num_restarts,
                     "pid": None}
                    for a in self._actors.values()]

    # ------------------------------------------------------------------
    # Placement groups (parity: gcs_placement_group_manager.h:223 +
    # 2PC prepare/commit of gcs_placement_group_scheduler.h:265)
    # ------------------------------------------------------------------
    def rpc_create_placement_group(self, pg_id: bytes,
                                   bundles: List[Dict[str, float]],
                                   strategy: str, name: str = "",
                                   slice_topology: str = "") -> None:
        pg = PlacementGroupInfo(pg_id, bundles, strategy, name,
                                slice_topology=slice_topology)
        with self._lock:
            self._pgs[pg_id] = pg
            self._log("pg", {"pg_id": pg_id, "bundles": bundles,
                             "strategy": strategy, "name": name,
                             "slice_topology": slice_topology})
        self._try_place_pg(pg)

    def _try_place_pg(self, pg: PlacementGroupInfo) -> None:
        """Pick nodes per strategy, then 2PC: prepare on every node; commit
        all on success, return-on-any-failure (retry later). Single-placer:
        concurrent triggers (registration handlers, retry timers, node-death
        replacement) collapse onto one in-flight attempt — two attempts
        committing different plans would leak the losing plan's bundles."""
        with self._lock:
            if pg.state != "PENDING" or pg.placing:
                return
            pg.placing = True
            live = [dict(v) for v in self._nodes.values() if v["alive"]]
        try:
            plan = self._plan_bundles(pg, live)
            if plan is None:
                self._schedule_pg_retry(pg)
                return
            prepared: List[Tuple[bytes, str, int]] = []
            ok = True
            for idx, node in enumerate(plan):
                try:
                    granted = get_client(node["address"]).call(
                        "prepare_bundle", pg_id=pg.pg_id, bundle_index=idx,
                        resources=pg.bundles[idx])
                except Exception:
                    granted = False
                if not granted:
                    ok = False
                    break
                prepared.append((node["node_id"], node["address"], idx))
            with self._lock:
                removed = pg.state == "REMOVED"
            if ok and not removed:
                for _, addr, idx in prepared:
                    try:
                        get_client(addr).call("commit_bundle", pg_id=pg.pg_id,
                                              bundle_index=idx)
                    except Exception:
                        pass
                with self._cv:
                    if pg.state == "REMOVED":
                        removed = True  # raced remove: roll back below
                    else:
                        pg.bundle_nodes = [n["node_id"] for n in plan]
                        pg.state = "CREATED"
                        self._log("pg_state", {
                            "pg_id": pg.pg_id, "state": pg.state,
                            "bundle_nodes": pg.bundle_nodes,
                            "slice_id": pg.slice_id})
                        self._cv.notify_all()
            if not ok or removed:
                for _, addr, idx in prepared:
                    try:
                        get_client(addr).call("return_bundle", pg_id=pg.pg_id,
                                              bundle_index=idx)
                    except Exception:
                        pass
                if not removed:
                    self._schedule_pg_retry(pg)
        finally:
            with self._lock:
                pg.placing = False

    def _schedule_pg_retry(self, pg: PlacementGroupInfo) -> None:
        """At most one pending retry timer per PG (triggers can arrive from
        every node registration; unchecked they'd multiply timer chains)."""
        with self._lock:
            if pg.retry_scheduled or pg.state != "PENDING":
                return
            pg.retry_scheduled = True

        def fire():
            with self._lock:
                pg.retry_scheduled = False
            self._try_place_pg(pg)

        threading.Timer(0.5, fire).start()

    def _plan_bundles(self, pg: PlacementGroupInfo,
                      live: List[dict]) -> Optional[List[dict]]:
        """STRICT_PACK: all on one node. PACK: prefer few nodes. SPREAD:
        round-robin distinct nodes. STRICT_SPREAD: distinct node per bundle.
        Bundle feasibility is checked against available resources."""
        def fits(avail, res):
            return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in res.items())

        avail = {n["node_id"]: dict(n["resources_available"]) for n in live}
        by_id = {n["node_id"]: n for n in live}

        def take(nid, res):
            for k, v in res.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v

        plan: List[dict] = []
        if pg.strategy == "SLICE":
            # ICI-contiguity: every bundle lands on hosts of ONE complete
            # slice, bundle i on the slice's rank-i host (so jax process
            # indices line up with TPU_WORKER_ID and collectives ride ICI).
            # A request no single slice can hold is refused (stays PENDING)
            # rather than silently spread across slices — stricter than the
            # reference's STRICT_PACK (one *node*), which is the closest
            # analog (gcs_placement_group_scheduler.h:265).
            with self._lock:
                slices = self._slice_view()
            for s in sorted(slices.values(), key=lambda s: s["slice_id"]):
                if not s["complete"]:
                    continue
                if pg.slice_topology and \
                        s["accelerator_type"] != pg.slice_topology:
                    continue
                if len(pg.bundles) > len(s["hosts"]):
                    continue
                ok = True
                for i, b in enumerate(pg.bundles):
                    host = s["hosts"][i]
                    if not fits(avail.get(host["node_id"], {}), b):
                        ok = False
                        break
                    take(host["node_id"], b)
                if ok:
                    pg.slice_id = s["slice_id"]
                    return [by_id[h["node_id"]] for h in
                            s["hosts"][:len(pg.bundles)]]
                # restore tentative takes before trying the next slice
                avail.update({n["node_id"]: dict(n["resources_available"])
                              for n in live})
            return None
        if pg.strategy in ("STRICT_PACK", "PACK"):
            order = sorted(live, key=lambda n: -sum(
                n["resources_available"].get(k, 0.0) for k in ("CPU", "TPU")))
            if pg.strategy == "STRICT_PACK":
                for n in order:
                    a = dict(avail[n["node_id"]])
                    if all(fits_and_take(a, b) for b in pg.bundles):
                        return [n] * len(pg.bundles)
                return None
            for b in pg.bundles:
                placed = False
                for n in plan + order:  # prefer already-used nodes (PACK)
                    nid = n["node_id"]
                    if fits(avail[nid], b):
                        take(nid, b)
                        plan.append(by_id[nid])
                        placed = True
                        break
                if not placed:
                    return None
            return plan
        # SPREAD / STRICT_SPREAD
        used: Set[bytes] = set()
        for b in pg.bundles:
            candidates = sorted(
                live, key=lambda n: (n["node_id"] in used, -sum(
                    avail[n["node_id"]].get(k, 0.0) for k in ("CPU", "TPU"))))
            placed = False
            for n in candidates:
                nid = n["node_id"]
                if pg.strategy == "STRICT_SPREAD" and nid in used:
                    continue
                if fits(avail[nid], b):
                    take(nid, b)
                    used.add(nid)
                    plan.append(n)
                    placed = True
                    break
            if not placed:
                return None
        return plan

    def rpc_pg_ready(self, pg_id: bytes, timeout: float = 0.0) -> dict:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    return {"state": "UNKNOWN"}
                if pg.state == "CREATED" or timeout <= 0:
                    return {"state": pg.state,
                            "bundle_nodes": list(pg.bundle_nodes),
                            "slice_id": pg.slice_id}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"state": pg.state,
                            "bundle_nodes": list(pg.bundle_nodes),
                            "slice_id": pg.slice_id}
                self._cv.wait(min(remaining, 1.0))

    def rpc_remove_placement_group(self, pg_id: bytes) -> None:
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            pg.state = "REMOVED"
            self._log("pg_removed", {"pg_id": pg_id})
            targets = [(self._nodes[n]["address"], i)
                       for i, n in enumerate(pg.bundle_nodes)
                       if n in self._nodes and self._nodes[n]["alive"]]
        for addr, idx in targets:
            try:
                get_client(addr).call("return_bundle", pg_id=pg_id,
                                      bundle_index=idx)
            except Exception:
                pass

    def rpc_list_placement_groups(self) -> List[dict]:
        with self._lock:
            return [{"pg_id": pg.pg_id.hex(), "state": pg.state,
                     "strategy": pg.strategy, "name": pg.name,
                     "slice_id": pg.slice_id,
                     "bundles": pg.bundles} for pg in self._pgs.values()]

    # ------------------------------------------------------------------
    # Task events / jobs (parity: gcs_task_manager.h:61, GcsJobManager)
    # ------------------------------------------------------------------
    def rpc_push_task_events(self, events: List[dict]) -> None:
        cap = int(config.get("task_event_buffer_size"))
        with self._lock:
            self._task_events.extend(events)
            if len(self._task_events) > cap:
                del self._task_events[:len(self._task_events) - cap]

    def rpc_get_task_events(self) -> List[dict]:
        with self._lock:
            return list(self._task_events)

    # Span ring (util/tracing.py sink; parity role:
    # util/tracing/tracing_helper.py -> OTLP collector).
    def rpc_push_spans(self, spans: List[dict]) -> None:
        with self._lock:
            if not hasattr(self, "_spans"):
                self._spans: List[dict] = []
            self._spans.extend(spans)
            if len(self._spans) > 65536:
                del self._spans[:len(self._spans) - 65536]

    def rpc_get_spans(self, trace_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            spans = list(getattr(self, "_spans", ()))
        if trace_id:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    # Flight-recorder event store (util/events.py sink; GcsTaskManager's
    # bounded-store role for the compact ring events every plane emits).
    def rpc_push_ring_events(self, node_id: str, pid: int, events,
                             dropped: int = 0) -> dict:
        recs = [{"ts": e[0], "kind": e[1], "ident": e[2], "value": e[3],
                 "attrs": e[4], "node_id": node_id, "pid": pid}
                for e in events]
        with self._ring_lock:
            self._ring_events.extend(recs)
            self._ring_dropped += int(dropped)
            if len(self._ring_events) > 200_000:
                del self._ring_events[:len(self._ring_events) - 200_000]
        return {"ok": True}

    def rpc_get_ring_events(self, limit: int = 0,
                            kind: Optional[str] = None) -> List[dict]:
        with self._ring_lock:
            evs = list(self._ring_events)
        if kind:
            evs = [e for e in evs
                   if e["kind"] == kind or e["kind"].startswith(kind + ".")]
        return evs[-limit:] if limit else evs

    def rpc_debug_state(self) -> dict:
        """Internal-table sizes + queue depths (raylet debug_state.txt
        parity, conductor slice). Cheap: counts only, no copies."""
        with self._lock:
            nodes_alive = sum(1 for n in self._nodes.values() if n["alive"])
            actor_states: Dict[str, int] = {}
            for a in self._actors.values():
                actor_states[a.state] = actor_states.get(a.state, 0) + 1
            kv_ns: Dict[str, int] = {}
            for (n, _k) in self._kv:
                kv_ns[n] = kv_ns.get(n, 0) + 1
            out = {
                "role": "conductor",
                "epoch": self._epoch,
                "nodes_alive": nodes_alive,
                "nodes_total": len(self._nodes),
                "actors": actor_states,
                "named_actors": len(self._named_actors),
                "functions": len(self._functions),
                "kv_keys_by_ns": kv_ns,
                "object_locations": len(self._object_locations),
                "objects_spilled": len(self._object_spilled),
                "objects_lost": len(self._lost_objects),
                "refcount_entries": len(self._refcounts),
                "ref_tombstones": len(self._ref_tombstones),
                "placement_groups": len(self._pgs),
                "task_events": len(self._task_events),
                "spans": len(getattr(self, "_spans", ())),
            }
        with self._free_cv:
            out["free_queue"] = len(self._free_q)
        with self._ring_lock:
            out["ring_events"] = len(self._ring_events)
            out["ring_events_dropped"] = self._ring_dropped
        out["cluster_events"] = len(self._events)
        return out

    def rpc_next_job_id(self) -> int:
        with self._lock:
            self._job_counter += 1
            self._log("job", {"counter": self._job_counter})
            return self._job_counter

    # ------------------------------------------------------------------
    # Worker-log pubsub (parity: the log channel of src/ray/pubsub +
    # python/ray/_private/log_monitor.py:104 — daemons tail worker files
    # and publish; drivers long-poll and print)
    # ------------------------------------------------------------------
    def rpc_push_logs(self, lines: List[dict]) -> None:
        with self._log_cv:
            for line in lines:
                self._log_seq += 1
                self._log_buffer.append((self._log_seq, line))
            self._log_cv.notify_all()

    def rpc_poll_logs(self, after_seq: int, timeout: float = 0.0) -> dict:
        deadline = time.monotonic() + timeout
        with self._log_cv:
            while True:
                if self._log_seq > after_seq:
                    # seqs are monotonic: walk back from the tail only as
                    # far as needed instead of scanning the whole ring
                    n = min(len(self._log_buffer),
                            self._log_seq - after_seq)
                    out = [l for s, l in list(self._log_buffer)[-n:]
                           if s > after_seq]
                    return {"lines": out, "seq": self._log_seq}
                if timeout <= 0:
                    return {"lines": [], "seq": self._log_seq}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"lines": [], "seq": self._log_seq}
                self._log_cv.wait(min(remaining, 1.0))

    def rpc_ping(self) -> str:
        return "pong"

    def stop(self) -> None:
        self._stopped = True
        self.server.stop()
        if self._journal is not None:
            self._journal.close()


def fits_and_take(avail: Dict[str, float], res: Dict[str, float]) -> bool:
    if any(avail.get(k, 0.0) + 1e-9 < v for k, v in res.items()):
        return False
    for k, v in res.items():
        avail[k] = avail.get(k, 0.0) - v
    return True
