"""multiprocessing.Pool-compatible API over cluster tasks.

Role parity: python/ray/util/multiprocessing — Pool whose workers are
cluster actors, so ``pool.map`` scales past one machine with the stdlib
interface.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional


class _PoolActor:
    def run(self, fn_blob: bytes, args: tuple) -> Any:
        import cloudpickle
        return cloudpickle.loads(fn_blob)(*args)

    def run_batch(self, fn_blob: bytes, items: list, star: bool) -> list:
        import cloudpickle
        fn = cloudpickle.loads(fn_blob)
        return [fn(*it) if star else fn(it) for it in items]


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu as rt
        outs = rt.get(self._refs, timeout=timeout)
        if self._single:
            return outs[0]
        return list(itertools.chain.from_iterable(outs))

    def wait(self, timeout: Optional[float] = None) -> None:
        import ray_tpu as rt
        rt.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu as rt
        done, _ = rt.wait(self._refs, num_returns=len(self._refs),
                          timeout=0)
        return len(done) == len(self._refs)


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        import multiprocessing

        import ray_tpu as rt
        if not rt.is_initialized():
            rt.init()
        n = processes or multiprocessing.cpu_count()
        opts = ray_remote_args or {"num_cpus": 1}
        cls = rt.remote(_PoolActor)
        self._actors = [cls.options(**opts).remote() for _ in range(n)]
        self._n = n
        self._closed = False

    def _chunks(self, items: List[Any], chunksize: Optional[int]):
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        for i in range(0, len(items), chunksize):
            yield items[i:i + chunksize]

    def _map_async(self, fn: Callable, iterable: Iterable, star: bool,
                   chunksize: Optional[int]) -> AsyncResult:
        import cloudpickle
        if self._closed:
            raise ValueError("Pool is closed")
        blob = cloudpickle.dumps(fn)
        items = list(iterable)
        refs = []
        for i, chunk in enumerate(self._chunks(items, chunksize)):
            actor = self._actors[i % self._n]
            refs.append(actor.run_batch.remote(blob, chunk, star))
        return AsyncResult(refs, single=False)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> list:
        return self._map_async(fn, iterable, False, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._map_async(fn, iterable, False, chunksize)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> list:
        return self._map_async(fn, iterable, True, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._map_async(fn, iterable, True, chunksize)

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        import cloudpickle
        if self._closed:
            raise ValueError("Pool is closed")
        kwds = kwds or {}
        blob = cloudpickle.dumps(lambda *a: fn(*a, **kwds))
        actor = self._actors[0]
        return AsyncResult([actor.run.remote(blob, args)], single=True)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        import ray_tpu as rt
        import cloudpickle
        blob = cloudpickle.dumps(fn)
        items = list(iterable)
        refs = [self._actors[i % self._n].run_batch.remote(blob, chunk,
                                                           False)
                for i, chunk in enumerate(self._chunks(items, chunksize))]
        for ref in refs:
            yield from rt.get(ref)

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        import ray_tpu as rt
        self._closed = True
        for a in self._actors:
            try:
                rt.kill(a)
            except Exception:
                pass

    def join(self) -> None:
        if not self._closed:
            raise ValueError("close() must precede join()")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.terminate()
        return False
