"""OTel-style spans for the task path.

Role parity: python/ray/util/tracing/tracing_helper.py — the reference
wraps remote-call submission and worker-side execution in OpenTelemetry
spans and propagates the trace context inside the task spec. Same shape
here without the otel dependency: spans are plain dicts
{trace_id, span_id, parent_id, name, start, end, attrs}, the context
rides the task dict ("trace_ctx"), and finished spans buffer locally
until flushed to the conductor's span ring (state.list_spans / the
dashboard read them; export to a real OTLP collector is a sink swap).

Enabled via the `tracing_enabled` flag (env RAY_TPU_TRACING_ENABLED=1 or
init(_system_config={"tracing_enabled": True})). Off = zero overhead on
the hot path beyond one flag read.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

_buffer: List[dict] = []
_lock = threading.Lock()
_enabled_gen: Optional[int] = None
_enabled_v = False

# Span/trace id minting: a per-process entropy nonce + counter. uuid4()
# draws urandom per call — 10-20us on entropy-starved hosts, which at
# thousands of traced submits/s dominated the whole tracing tax — while
# the nonce keeps ids unique across processes at f-string cost.
_nonce = os.urandom(12).hex()           # 24 hex chars
_ids = itertools.count()


def _span_id() -> str:
    return f"{_nonce[:8]}{next(_ids) & 0xFFFFFFFF:08x}"


def _trace_id() -> str:
    return f"{_nonce}{next(_ids) & 0xFFFFFFFF:08x}"


def enabled() -> bool:
    # Cached against the config generation: this flag read sits on every
    # task submission (config.get walks os.environ — measurable at
    # thousands of submits/s).
    global _enabled_gen, _enabled_v
    from ray_tpu import config
    if _enabled_gen != config.generation:
        _enabled_v = bool(config.get("tracing_enabled"))
        _enabled_gen = config.generation
    return _enabled_v


def new_context(parent: Optional[dict] = None) -> dict:
    """A fresh span context; child of ``parent`` when given."""
    if parent:
        return {"trace_id": parent.get("trace_id") or _trace_id(),
                "span_id": _span_id(),
                "parent_id": parent.get("span_id")}
    return {"trace_id": _trace_id(), "span_id": _span_id(),
            "parent_id": None}


def record(name: str, start: float, end: float, ctx: dict,
           attrs: Optional[Dict[str, Any]] = None) -> None:
    with _lock:
        _buffer.append({
            "name": name, "start": start, "end": end,
            "trace_id": ctx["trace_id"], "span_id": ctx["span_id"],
            "parent_id": ctx.get("parent_id"),
            "attrs": dict(attrs or {}),
        })
        if len(_buffer) > 65536:
            del _buffer[:len(_buffer) - 65536]


@contextlib.contextmanager
def span(name: str, parent: Optional[dict] = None,
         attrs: Optional[Dict[str, Any]] = None):
    """Context manager: times the body, records on exit, yields the span
    context for propagation (stick it in the task dict)."""
    if not enabled():
        yield None
        return
    ctx = new_context(parent)
    start = time.time()
    error = None
    try:
        yield ctx
    except BaseException as e:  # noqa: BLE001 - annotated and re-raised
        error = repr(e)
        raise
    finally:
        a = dict(attrs or {})
        if error:
            a["error"] = error
        record(name, start, time.time(), ctx, a)


def drain() -> List[dict]:
    with _lock:
        out, _buffer[:] = list(_buffer), []
    return out


def flush(conductor_client) -> None:
    """Ship buffered spans to the conductor ring; re-buffers on failure."""
    spans = drain()
    if not spans:
        return
    try:
        conductor_client.call("push_spans", spans=spans)
    except Exception:
        with _lock:
            _buffer[:0] = spans  # retry on the next flush
