"""OTel-style spans for the task path.

Role parity: python/ray/util/tracing/tracing_helper.py — the reference
wraps remote-call submission and worker-side execution in OpenTelemetry
spans and propagates the trace context inside the task spec. Same shape
here without the otel dependency: spans are plain dicts
{trace_id, span_id, parent_id, name, start, end, attrs}, the context
rides the task dict ("trace_ctx"), and finished spans buffer locally
until flushed to the conductor's span ring (state.list_spans / the
dashboard read them; export to a real OTLP collector is a sink swap).

Enabled via the `tracing_enabled` flag (env RAY_TPU_TRACING_ENABLED=1 or
init(_system_config={"tracing_enabled": True})). Off = zero overhead on
the hot path beyond one flag read.
"""

from __future__ import annotations

import contextlib
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_buffer: List[dict] = []
_lock = threading.Lock()
_enabled_gen: Optional[int] = None
_enabled_v = False


def enabled() -> bool:
    # Cached against the config generation: this flag read sits on every
    # task submission (config.get walks os.environ — measurable at
    # thousands of submits/s).
    global _enabled_gen, _enabled_v
    from ray_tpu import config
    if _enabled_gen != config.generation:
        _enabled_v = bool(config.get("tracing_enabled"))
        _enabled_gen = config.generation
    return _enabled_v


def new_context(parent: Optional[dict] = None) -> dict:
    """A fresh span context; child of ``parent`` when given."""
    return {
        "trace_id": (parent or {}).get("trace_id") or uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": (parent or {}).get("span_id"),
    }


def record(name: str, start: float, end: float, ctx: dict,
           attrs: Optional[Dict[str, Any]] = None) -> None:
    with _lock:
        _buffer.append({
            "name": name, "start": start, "end": end,
            "trace_id": ctx["trace_id"], "span_id": ctx["span_id"],
            "parent_id": ctx.get("parent_id"),
            "attrs": dict(attrs or {}),
        })
        if len(_buffer) > 65536:
            del _buffer[:len(_buffer) - 65536]


@contextlib.contextmanager
def span(name: str, parent: Optional[dict] = None,
         attrs: Optional[Dict[str, Any]] = None):
    """Context manager: times the body, records on exit, yields the span
    context for propagation (stick it in the task dict)."""
    if not enabled():
        yield None
        return
    ctx = new_context(parent)
    start = time.time()
    error = None
    try:
        yield ctx
    except BaseException as e:  # noqa: BLE001 - annotated and re-raised
        error = repr(e)
        raise
    finally:
        a = dict(attrs or {})
        if error:
            a["error"] = error
        record(name, start, time.time(), ctx, a)


def drain() -> List[dict]:
    with _lock:
        out, _buffer[:] = list(_buffer), []
    return out


def flush(conductor_client) -> None:
    """Ship buffered spans to the conductor ring; re-buffers on failure."""
    spans = drain()
    if not spans:
        return
    try:
        conductor_client.call("push_spans", spans=spans)
    except Exception:
        with _lock:
            _buffer[:0] = spans  # retry on the next flush
