"""User-defined metrics: Counter / Gauge / Histogram.

Role parity: python/ray/util/metrics.py (Cython metric.pxi + OpenCensus
export behind it). Metrics register in a per-process registry; a background
flusher ships them to the conductor KV under the "metrics" namespace, and
``prometheus_text()`` renders the cluster-wide scrape payload (the role of
the per-node MetricsAgent -> Prometheus pipeline,
_private/metrics_agent.py:375).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_registry: Dict[str, "Metric"] = {}
_registry_lock = threading.Lock()
_flusher_started = False
_node_hex = ""   # set by events.configure; disambiguates the KV key


def set_node(node_hex: str) -> None:
    """Bind this process's metrics snapshots to a node identity. The KV
    key must be unique per (node, pid): two workers on different nodes
    can share an OS pid, and a bare ``proc-{pid}`` key made them
    overwrite each other's snapshots."""
    global _node_hex
    _node_hex = node_hex


def _kv_key() -> bytes:
    return f"proc-{_node_hex}-{os.getpid()}".encode()


_builtin_lock = threading.Lock()

# Canonical registry of built-in runtime metric names (the ``rt_`` prefix
# is reserved). ``builtin()`` refuses unminted rt_* names, and rtcheck's
# name-drift checker enforces the same invariant statically: every rt_*
# literal in the tree must appear here, and every entry here must be
# referenced somewhere outside this module.
METRICS: Dict[str, str] = {
    # task plane
    "rt_tasks_submitted_total": "tasks submitted by this driver",
    "rt_tasks_executed_total": "tasks executed by this worker",
    "rt_task_exec_s": "task execution wall time",
    "rt_task_replies_total": "task replies observed by the driver",
    "rt_task_retries_total": "task retries scheduled after failures",
    "rt_lease_latency_s": "worker-lease grant latency",
    "rt_actor_push_window": "actor ordered-push window occupancy",
    # rpc plane
    "rt_rpc_frame_latency_s": "rpc frame round-trip latency",
    "rt_rpc_frames_total": "rpc frames sent",
    "rt_rpc_frame_bytes_total": "rpc frame payload bytes",
    "rt_rpc_inflight": "rpc requests currently in flight",
    "rt_rpc_channels": "open rpc channels in this process",
    # object plane
    "rt_pull_windows_total": "pull windows granted",
    "rt_pull_bytes_total": "bytes fetched by pulls",
    "rt_pull_failovers_total": "pull chunk failovers to another source",
    "rt_pull_shm_direct_total": "pulls satisfied shm-direct (same host)",
    "rt_pull_inflight_bytes": "bytes currently in flight across pulls",
    "rt_pull_budget_waiters": "pulls waiting on the inflight budget",
    "rt_push_bytes_total": "bytes pushed by the push manager",
    "rt_put_backpressure_total": "puts delayed by store backpressure",
    "rt_inline_cache_hits_total": "inline (small-object) cache hits",
    "rt_inline_cache_misses_total": "inline cache misses",
    "rt_inline_cache_entries": "inline cache entries resident",
    "rt_inline_cache_bytes": "inline cache bytes resident",
    "rt_inline_pending_returns": "inline returns awaiting seal",
    "rt_inline_seals_total": "inline returns sealed",
    "rt_location_batch_backlog": "location-update batches queued",
    # device-native array objects (r16)
    "rt_array_puts_total": "array objects stored via the zero-copy path",
    "rt_array_put_bytes_total": "bytes stored via the array fast path",
    "rt_array_pins_live": "read-only array views pinning shm mappings",
    "rt_bcast_total": "collective-backed object broadcasts completed",
    "rt_bcast_legs_total": "broadcast tree legs completed",
    "rt_bcast_bytes_total": "bytes moved by broadcast tree legs",
    "rt_bcast_fallback_total": "broadcast members re-striped onto the "
                               "classic pull path",
    # spill / evict tier
    "rt_spill_objects_total": "primaries spilled to the durable tier",
    "rt_spill_bytes_total": "bytes spilled to the durable tier",
    "rt_spill_restores_total": "objects restored from spill",
    "rt_spill_restore_bytes_total": "bytes restored from spill",
    "rt_spill_restored_objects": "objects currently restored from spill",
    "rt_spill_restored_bytes": "bytes currently restored from spill",
    "rt_evict_objects_total": "shm copies evicted after spill",
    "rt_evict_bytes_total": "shm bytes evicted after spill",
    # compiled graphs
    "rt_cgraph_executes_total": "compiled-graph executions",
    "rt_cgraph_slot_writes_total": "compiled-graph channel slot writes",
    "rt_cgraph_slot_write_s": "channel slot write latency",
    "rt_cgraph_slot_wait_s": "channel slot wait (reader blocked)",
    # train pipeline
    "rt_pipeline_steps_total": "pipeline steps completed",
    "rt_pipeline_stage_ops_total": "pipeline stage ops executed",
    "rt_pipeline_stage_op_s": "pipeline stage op wall time",
    "rt_pipeline_efficiency": "pipeline efficiency (busy/total)",
    # serve ingress
    "rt_serve_requests_total": "serve requests admitted",
    "rt_serve_request_s": "serve request end-to-end latency",
    "rt_serve_shed_total": "serve requests shed (503)",
    "rt_serve_timeout_total": "serve requests timed out",
    "rt_serve_retries_total": "serve handle retries",
    "rt_serve_drains_total": "replica graceful drains",
    "rt_serve_batch_size": "adaptive-batch flush size",
    "rt_serve_batch_window_ms": "adaptive-batch window",
    "rt_serve_p99_ms": "proxy-observed p99 latency",
    "rt_serve_queued": "proxy requests queued",
    "rt_serve_ongoing": "proxy requests ongoing",
    "rt_serve_replica_ongoing": "per-replica ongoing requests",
    # infrastructure
    "rt_faults_fired_total": "fault-plane rules fired",
    "rt_events_dropped_total": "flight-recorder events dropped",
    # lock sanitizer
    "rt_lock_cycles_total": "lock-order cycles detected by lockcheck",
    "rt_lock_long_holds_total": "lock holds past lockcheck_hold_s",
}


def builtin(cls, name: str, description: str = "", **kwargs) -> "Metric":
    """Get-or-create a built-in runtime metric by name (the flight
    recorder folds ring events into these off the hot path). rt_* names
    must be minted in ``METRICS`` — drift between emit sites and the
    registry is exactly what this and rtcheck's name-drift pass catch."""
    m = _registry.get(name)
    if m is None:
        if name.startswith("rt_") and name not in METRICS:
            raise ValueError(
                f"built-in metric {name!r} is not minted in "
                f"metrics.METRICS (rt_* names are reserved)")
        with _builtin_lock:
            m = _registry.get(name)
            if m is None:
                m = cls(name, description or METRICS.get(name, ""),
                        **kwargs)
    return m


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        if not name.replace("_", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple((k, merged.get(k, "")) for k in self.tag_keys)

    def _points(self) -> List[Tuple[Tuple, float]]:
        with self._lock:
            return list(self._values.items())

    kind = "gauge"


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._tag_tuple(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._values[key] = value  # last observation (gauge view)

    def _hist_points(self):
        with self._lock:
            return ({k: list(v) for k, v in self._counts.items()},
                    dict(self._sums))


def _snapshot() -> dict:
    out = {}
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        entry = {"kind": m.kind, "description": m.description,
                 "points": [(list(k), v) for k, v in m._points()]}
        if isinstance(m, Histogram):
            counts, sums = m._hist_points()
            # Keep the tag tuples structured (not stringified): the
            # exposition renderer needs them back as label pairs.
            entry["histogram"] = {
                "boundaries": m.boundaries,
                "series": [(list(k), v, sums.get(k, 0.0))
                           for k, v in counts.items()],
            }
        out[m.name] = entry
    return out


def _flush_once() -> None:
    import pickle
    try:
        from ray_tpu.core.api import _global_runtime, is_initialized
        if not is_initialized():
            return
        rt = _global_runtime()
        conductor = getattr(rt, "conductor", None)
        if conductor is None:
            return
        conductor.call("kv_put", ns="metrics", key=_kv_key(),
                       value=pickle.dumps(_snapshot(), protocol=5))
    except Exception:
        pass


def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    _flusher_started = True

    def loop():
        from ray_tpu import config
        while True:
            time.sleep(config.get("metrics_export_period_s"))
            _flush_once()

    threading.Thread(target=loop, daemon=True, name="metrics-flush").start()


def prometheus_text() -> str:
    """Render every process's shipped metrics in Prometheus exposition
    format (scrape endpoint payload)."""
    import pickle
    from ray_tpu.core.api import _global_runtime
    rt = _global_runtime()
    conductor = rt.conductor
    _flush_once()
    lines: List[str] = []
    seen_help = set()
    for key in conductor.call("kv_keys", ns="metrics"):
        blob = conductor.call("kv_get", ns="metrics", key=key)
        if blob is None:
            continue
        snap = pickle.loads(blob)
        for name, entry in snap.items():
            if name not in seen_help:
                lines.append(f"# HELP {name} {entry['description']}")
                lines.append(f"# TYPE {name} {entry['kind']}")
                seen_help.add(name)
            hist = entry.get("histogram")
            if hist and "series" in hist:
                # Proper histogram exposition: cumulative _bucket lines
                # per le boundary (+Inf last), then _sum and _count —
                # the last-observation gauge view is NOT rendered (one
                # name must expose one type).
                bounds = hist["boundaries"]
                for tags, counts, total in hist["series"]:
                    base = [f'{k}="{v}"' for k, v in tags]
                    cum = 0
                    for b, c in zip(list(bounds) + ["+Inf"], counts):
                        cum += c
                        label = ",".join(base + [f'le="{b}"'])
                        lines.append(f'{name}_bucket{{{label}}} {cum}')
                    label = "{" + ",".join(base) + "}" if base else ""
                    lines.append(f"{name}_sum{label} {total}")
                    lines.append(f"{name}_count{label} {cum}")
                continue
            for tags, value in entry["points"]:
                label = ",".join(f'{k}="{v}"' for k, v in tags)
                label = "{" + label + "}" if label else ""
                lines.append(f"{name}{label} {value}")
    return "\n".join(lines) + "\n"
