"""Utility layer: placement groups, scheduling strategies, actor pool,
distributed queue (parity: python/ray/util/)."""

from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group,
                                          placement_group_table)
from ray_tpu.util.scheduling_strategies import (
    PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy,
    SliceSchedulingStrategy)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue

__all__ = [
    "PlacementGroup", "placement_group", "remove_placement_group",
    "placement_group_table", "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy", "SliceSchedulingStrategy",
    "ActorPool", "Queue",
]
