"""Lock-order sanitizer for the named control-plane locks.

Role parity: the reference leans on clang's thread-safety annotations
(``GUARDED_BY``/``ACQUIRED_AFTER``) and TSAN suites to keep its C++
control plane deadlock-free. Python has neither, so this module gives
the coarse per-plane locks (conductor state, daemon state, object plane,
serve controller) a runtime sanitizer instead:

- every ``NamedLock`` acquisition records a per-thread held-lock stack
  (``threading.local``) and, per process, the acquisition-order edges
  between named locks ("held A, then took B");
- a new edge runs a DFS over the edge graph — a path back to the source
  is a lock-order cycle, i.e. a potential deadlock, reported once per
  cycle signature as a ``lock.cycle`` flight-recorder event and counted
  in ``rt_lock_cycles_total``;
- releasing a lock held longer than ``lockcheck_hold_s`` reports
  ``lock.long_hold`` / ``rt_lock_long_holds_total`` (a long hold on a
  control-plane lock is the precursor to every "conductor froze"
  incident r07/r13 chased).

Disabled cost (the default): ``acquire``/``release`` do one cached
generation compare — the fault_plane pattern — and delegate straight to
the wrapped ``threading.Lock``. The sanitizer arms process-wide via the
``lockcheck_enabled`` config flag; tests/conftest.py flips it for the
conductor/daemon/serve-heavy modules and asserts ``cycles()`` stays
empty.

``NamedLock`` deliberately implements only ``acquire``/``release`` (not
``_release_save``/``_acquire_restore``/``_is_owned``), so
``threading.Condition(NamedLock(...))`` uses its portable fallback path
and every ``cv.wait()`` release/reacquire passes through the sanitizer.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu import config

_enabled_gen: Optional[int] = None
_enabled_v = False
_hold_s = 0.0

# acquisition-order edges: held lock name -> names acquired while held
_graph_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}
_cycles: List[Tuple[str, ...]] = []
_cycle_sigs: Set[Tuple[str, ...]] = set()
_long_holds: List[Tuple[str, float]] = []

_tls = threading.local()


def _enabled() -> bool:
    global _enabled_gen, _enabled_v, _hold_s
    if _enabled_gen != config.generation:
        _enabled_v = bool(config.get("lockcheck_enabled"))
        _hold_s = float(config.get("lockcheck_hold_s"))
        _enabled_gen = config.generation
    return _enabled_v


def _held_stack() -> List[Tuple[str, float]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _find_cycle(src: str, dst: str) -> Optional[Tuple[str, ...]]:
    """Path dst ->* src in the edge graph (new edge src->dst closes it)."""
    path = [dst]
    seen = {dst}

    def dfs(node: str) -> bool:
        for nxt in _edges.get(node, ()):
            if nxt == src:
                path.append(src)
                return True
            if nxt not in seen:
                seen.add(nxt)
                path.append(nxt)
                if dfs(nxt):
                    return True
                path.pop()
        return False

    return tuple([src] + path) if dfs(dst) else None


def _report_cycle(cycle: Tuple[str, ...]) -> None:
    # Canonical signature: rotate so the lexicographically-smallest name
    # leads — A->B->A and B->A->B are the same cycle.
    body = cycle[:-1]
    i = body.index(min(body))
    sig = body[i:] + body[:i]
    if sig in _cycle_sigs:
        return
    _cycle_sigs.add(sig)
    _cycles.append(cycle)
    try:
        from ray_tpu.util import events
        events.emit("lock.cycle", ident=cycle[0],
                    attrs={"cycle": "->".join(cycle)})
    except Exception:
        pass


def _note_acquired(name: str) -> None:
    stack = _held_stack()
    if stack:
        holder = stack[-1][0]
        if holder != name:
            with _graph_lock:
                targets = _edges.setdefault(holder, set())
                if name not in targets:
                    targets.add(name)
                    cycle = _find_cycle(holder, name)
                    if cycle:
                        _report_cycle(cycle)
    stack.append((name, time.monotonic()))


def _note_released(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            _, t0 = stack.pop(i)
            held = time.monotonic() - t0
            if _hold_s > 0 and held > _hold_s:
                with _graph_lock:
                    _long_holds.append((name, held))
                try:
                    from ray_tpu.util import events
                    events.emit("lock.long_hold", ident=name, value=held)
                except Exception:
                    pass
            return
    # Acquired while the sanitizer was off, released after it armed (or
    # vice versa): nothing to unwind.


class NamedLock:
    """A ``threading.Lock`` with a name and an optional order sanitizer.

    Drop-in for the control-plane ``self._lock`` attributes, including
    as the underlying lock of a ``threading.Condition``.
    """

    __slots__ = ("name", "_inner")

    def __init__(self, name: str,
                 inner: Optional[threading.Lock] = None):
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _enabled():
            _note_acquired(self.name)
        return got

    def release(self) -> None:
        if _enabled():
            _note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<NamedLock {self.name} {self._inner!r}>"


def named_lock(name: str) -> NamedLock:
    """The way planes mint their coarse state locks."""
    return NamedLock(name)


def cycles() -> List[Tuple[str, ...]]:
    """Lock-order cycles seen in this process (test assertions)."""
    with _graph_lock:
        return list(_cycles)


def long_holds() -> List[Tuple[str, float]]:
    with _graph_lock:
        return list(_long_holds)


def edges() -> Dict[str, Set[str]]:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def reset() -> None:
    """Forget the edge graph and findings (between tests)."""
    with _graph_lock:
        _edges.clear()
        _cycles.clear()
        _cycle_sigs.clear()
        _long_holds.clear()
