"""Distributed FIFO queue backed by an async actor (parity:
python/ray/util/queue.py — Queue over an _QueueActor)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self.q.put(item)
            return True
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return True, await self.q.get()
        try:
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def put_nowait(self, item) -> bool:
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return True, self.q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu as rt
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 64)
        self.actor = rt.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import ray_tpu as rt
        if not block:
            if not rt.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        if not rt.get(self.actor.put.remote(item, timeout)):
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import ray_tpu as rt
        if not block:
            ok, item = rt.get(self.actor.get_nowait.remote())
        else:
            ok, item = rt.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        import ray_tpu as rt
        return rt.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu as rt
        return rt.get(self.actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu as rt
        return rt.get(self.actor.full.remote())

    def shutdown(self) -> None:
        import ray_tpu as rt
        rt.kill(self.actor)
