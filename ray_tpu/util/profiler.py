"""In-process sampling profiler: flamegraph-able stack dumps on demand.

Role parity: dashboard/modules/reporter/profile_manager.py — the
reference shells out to py-spy to sample a worker. py-spy isn't in this
image, and a TPU worker's interesting stacks are PYTHON stacks (the
device work is asynchronous XLA); sampling ``sys._current_frames`` from
inside the target process gives the same flamegraph for zero
dependencies, triggered over the worker's existing RPC server — no
ptrace, works under any container seccomp policy.

Output format: collapsed stacks ("frame;frame;frame count" lines) —
feed straight to flamegraph.pl or speedscope.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict, Optional


def _format_frame(frame) -> str:
    code = frame.f_code
    return f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}"


def sample_once(exclude_thread: Optional[int] = None) -> Dict[str, int]:
    """One snapshot of every thread's stack -> {collapsed_stack: 1}."""
    out: Dict[str, int] = {}
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        if tid == exclude_thread:
            continue
        stack = []
        f = frame
        while f is not None:
            stack.append(_format_frame(f))
            f = f.f_back
        key = names.get(tid, str(tid)) + ";" + ";".join(reversed(stack))
        out[key] = out.get(key, 0) + 1
    return out


def collect(duration_s: float = 1.0, interval_s: float = 0.01) -> str:
    """Sample this process for ``duration_s``; returns collapsed-stack
    text. The sampling thread excludes itself."""
    counts: Counter = Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        counts.update(sample_once(exclude_thread=me))
        time.sleep(interval_s)
    return "\n".join(f"{stack} {n}" for stack, n in
                     sorted(counts.items(), key=lambda kv: -kv[1]))
