"""Flight-recorder event ring: near-free lifecycle events on every plane.

Role parity: task_event_buffer.h:188 (bounded, buffered, asynchronously
shipped task events) + profile_event.h (compact per-process profile
events merged into one cluster timeline). Every plane calls

    events.emit("pull.chunk", ident=oid_hex, value=nbytes)

and pays one cached-flag check, a tuple build, and a ring-slot store —
no RPC, no allocation growth (the ring is preallocated and overwrites
the oldest entry when full, counting what it dropped). A background
flusher ships ring deltas — and any buffered tracing spans — to the
conductor in batches, so NOTHING on the submit/execute/pull hot paths
performs a synchronous conductor RPC (the pre-r10 ``tracing.flush``
calls did exactly that and halved the task fast path when enabled).
Processes that already run a periodic conductor RPC (the node daemon's
heartbeat) piggyback their delta on it via ``heartbeat_payload()``
instead of paying a second connection.

Event shape (a plain tuple — cheapest thing that pickles):

    (ts, kind, ident, value, attrs)

``kind`` is a dotted event name ("task.submit", "rpc.frame", ...),
``ident`` an optional correlation id (task id hex, object id hex),
``value`` a number whose meaning the kind fixes (latency seconds,
bytes, window occupancy), ``attrs`` an optional small dict.

On top of the ring:

- the flusher folds drained events into the built-in per-plane metrics
  registry (util/metrics.py) — counters/histograms update in batch off
  the hot path (metrics_agent role);
- ``register_probe`` lets planes expose point-in-time gauges (RPC
  in-flight, cache sizes) sampled once per flush instead of per call;
- a slow-op watchdog (``watch_begin``/``watch_end``) reports any
  task/pull/RPC outliving ``slow_op_threshold_s`` to the conductor as
  a structured cluster event carrying the surrounding ring context.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import config

# Canonical registry of flight-recorder event kinds: every
# ``emit("…")`` literal in the tree must be minted here (rtcheck's
# name-drift checker enforces both directions; ``test.*`` kinds used by
# the test suite live outside the scanned tree). The doc states what
# ``value`` means for the kind.
EVENT_KINDS: Dict[str, str] = {
    # task plane
    "task.submit": "value unused; ident = task id",
    "task.exec": "value = execution seconds",
    "task.reply": "value = end-to-end seconds",
    "task.retry": "value = retries remaining",
    "lease.grant": "value = lease latency seconds",
    "actor.window": "value = ordered-push window occupancy",
    "inline.seal": "value = sealed inline bytes",
    # rpc plane
    "rpc.frame": "value = frame round-trip seconds; attrs carry bytes",
    # object plane
    "pull.window": "value = window bytes granted",
    "pull.chunk": "value = chunk bytes fetched",
    "pull.done": "value = total pulled bytes",
    "pull.failover": "value = failed-source ordinal",
    "pull.shm_direct": "value = bytes served shm-direct",
    "push.chunk": "value = chunk bytes pushed",
    "object.put.backpressure": "value = delay seconds",
    "inline.hit": "value = inline bytes served from cache",
    "inline.miss": "value unused; ident = object id",
    # device-native array objects (r16)
    "object.array.put": "value = array blob bytes stored zero-copy",
    "object.bcast.leg": "value = bytes moved by one broadcast tree leg",
    "object.bcast.done": "value = broadcast seconds; attrs carry "
                         "members/bytes/fallback",
    "object.bcast.fallback": "value = members re-striped onto the "
                             "classic pull path",
    # spill / evict tier
    "object.spill.write": "value = bytes spilled",
    "object.spill.restore": "value = bytes restored",
    "object.evict": "value = shm bytes evicted",
    # compiled graphs
    "cgraph.execute": "value = execution seconds",
    "cgraph.slot.write": "value = slot write seconds",
    "cgraph.slot.wait": "value = reader-blocked seconds",
    "pipeline.stage.op": "value = stage op seconds",
    "pipeline.step": "value = step seconds",
    # serve ingress
    "serve.request": "value = request seconds",
    "serve.shed": "value unused; attrs carry reason",
    "serve.timeout": "value = deadline seconds",
    "serve.retry": "value = attempt ordinal",
    "serve.drain": "value = drained ongoing count",
    "serve.batch.flush": "value = batch size; attrs carry window",
    # infrastructure
    "fault.fired": "value unused; ident = site, attrs carry action",
    "lock.cycle": "value unused; attrs carry the lock cycle",
    "lock.long_hold": "value = hold seconds; ident = lock name",
}

_lock = threading.Lock()
_buf: List[Any] = []
_cap = 0
_seq = 0          # next write position (monotonic over process life)
_cursor = 0       # first event not yet shipped
_dropped = 0      # overwritten-before-shipping count

_enabled_gen: Optional[int] = None
_enabled_v = False

_node_hex = ""
_conductor_addr: Optional[str] = None
_flusher: Optional[threading.Thread] = None
_flusher_lock = threading.Lock()
_flush_stop = threading.Event()

# Drained-but-unacked delta: drain() advances the cursor before the ship
# RPC, so a failed push must park its events here for the next tick or a
# busy conductor silently loses them (metrics are folded exactly once, on
# the first attempt).
_ship_lock = threading.Lock()
_unshipped: List[tuple] = []
_unshipped_dropped = 0

# slow-op watchdog: token -> (kind, ident, start_ts)
_watch_lock = threading.Lock()
_watch: Dict[int, Tuple[str, Optional[str], float]] = {}
_watch_next = 0
_watch_reported: set = set()

# point-in-time gauge probes: name -> fn() -> {metric_name: value}
_probes: Dict[str, Callable[[], Dict[str, float]]] = {}

# in-flight op scans for the watchdog: name -> fn() -> [(kind, ident,
# elapsed_s)]. Planes that already track their in-flight work (the
# pipelined RPC channels' meta sidecars) expose it here instead of
# paying per-op watch_begin/watch_end registration.
_inflight_scans: Dict[str, Callable[[], List[tuple]]] = {}
_scan_reported: set = set()


def enabled() -> bool:
    """Cached flag read (config.get walks os.environ — too hot for a
    per-event call; same pattern as tracing.enabled)."""
    global _enabled_gen, _enabled_v
    if _enabled_gen != config.generation:
        _refresh()
    return _enabled_v


def _refresh() -> None:
    global _enabled_gen, _enabled_v, _buf, _cap
    _enabled_v = bool(config.get("events_enabled"))
    _enabled_gen = config.generation
    if _enabled_v and not _cap:
        with _lock:
            if not _cap:
                cap = max(64, int(config.get("event_ring_size")))
                _buf = [None] * cap
                _cap = cap


def emit(kind: str, ident: Optional[str] = None, value: float = 0.0,
         attrs: Optional[dict] = None) -> None:
    """Append one event to the ring. O(1), never blocks on I/O."""
    if not enabled():
        return
    global _seq
    ev = (time.time(), kind, ident, value, attrs)
    with _lock:
        _buf[_seq % _cap] = ev
        _seq += 1


def snapshot(limit: int = 0) -> List[tuple]:
    """Current ring contents, oldest first (debug dumps / watchdog
    context). Does not move the flush cursor."""
    with _lock:
        if not _cap or _seq == 0:
            return []
        start = max(0, _seq - _cap)
        evs = [_buf[i % _cap] for i in range(start, _seq)]
    return evs[-limit:] if limit and limit < len(evs) else evs


def drain() -> Tuple[List[tuple], int]:
    """Events appended since the last drain (oldest first) plus how many
    were overwritten before they could ship."""
    global _cursor, _dropped
    with _lock:
        if not _cap:
            return [], 0
        end = _seq
        start = _cursor
        if end - start > _cap:
            _dropped += (end - _cap) - start
            start = end - _cap
        evs = [_buf[i % _cap] for i in range(start, end)]
        _cursor = end
        d, _dropped = _dropped, 0
    return evs, d


# ----------------------------------------------------------------------
# slow-op watchdog
# ----------------------------------------------------------------------
def watch_begin(kind: str, ident: Optional[str] = None) -> Optional[int]:
    """Register an in-flight op with the watchdog. Returns a token for
    watch_end, or None when events are disabled (watch_end(None) is a
    no-op, so call sites need no branching)."""
    if not enabled():
        return None
    global _watch_next
    with _watch_lock:
        token = _watch_next
        _watch_next += 1
        _watch[token] = (kind, ident, time.time())
    return token


def watch_end(token: Optional[int]) -> None:
    if token is None:
        return
    with _watch_lock:
        _watch.pop(token, None)
        _watch_reported.discard(token)


def _check_slow_ops(cli) -> None:
    thr = float(config.get("slow_op_threshold_s"))
    if thr <= 0:
        return
    now = time.time()
    with _watch_lock:
        slow = [(tok, k, i, now - t0)
                for tok, (k, i, t0) in _watch.items()
                if now - t0 > thr and tok not in _watch_reported]
        for tok, *_ in slow:
            _watch_reported.add(tok)
    # Registration-free ops (RPC frames): scan, dedup on approximate
    # start time (the same stuck op reports once across sweeps), prune
    # keys whose op finished.
    live = set()
    for fn in list(_inflight_scans.values()):
        try:
            for kind, ident, elapsed in fn():
                key = (kind, ident, round(now - elapsed, 1))
                live.add(key)
                if elapsed > thr and key not in _scan_reported:
                    _scan_reported.add(key)
                    slow.append((key, kind, ident, elapsed))
        except Exception:
            pass
    _scan_reported.intersection_update(live)
    for tok, kind, ident, elapsed in slow:
        try:
            cli.call(
                "report_event", severity="WARNING",
                source=f"events-{_node_hex[:8]}-{os.getpid()}",
                event_type="SLOW_OPERATION",
                message=f"{kind} {ident or ''} in flight for "
                        f"{elapsed:.1f}s (> {thr}s)",
                metadata={"kind": kind, "ident": ident,
                          "elapsed_s": round(elapsed, 3),
                          "pid": os.getpid(),
                          "ring_tail": snapshot(limit=50)})
        except Exception:
            if isinstance(tok, int):
                with _watch_lock:
                    _watch_reported.discard(tok)  # retry next sweep
            else:
                _scan_reported.discard(tok)


# ----------------------------------------------------------------------
# gauge probes (sampled once per flush, zero hot-path cost)
# ----------------------------------------------------------------------
def register_probe(name: str,
                   fn: Callable[[], Dict[str, float]]) -> None:
    """Register a callable returning {metric_name: value} gauges,
    sampled by the flusher (RPC in-flight, cache sizes, store usage)."""
    _probes[name] = fn


def register_inflight_scan(name: str,
                           fn: Callable[[], List[tuple]]) -> None:
    """Register a callable returning [(kind, ident, elapsed_s)] for ops
    currently in flight. The watchdog sweeps these alongside
    watch_begin-registered ops — the zero-hot-path-cost alternative for
    planes that already track their outstanding work."""
    _inflight_scans[name] = fn


def _sample_probes() -> None:
    from ray_tpu.util import metrics as _metrics
    for fn in list(_probes.values()):
        try:
            for name, value in fn().items():
                _metrics.builtin(_metrics.Gauge, name).set(value)
        except Exception:
            pass


# ----------------------------------------------------------------------
# event -> built-in metrics folding (runs in the flusher, not inline)
# ----------------------------------------------------------------------
def _fold_metrics(evs: List[tuple], dropped: int) -> None:
    from ray_tpu.util import metrics as m
    C, H = m.Counter, m.Histogram
    for ev in evs:
        kind, value, attrs = ev[1], ev[3], ev[4]
        if kind == "task.submit":
            m.builtin(C, "rt_tasks_submitted_total").inc()
        elif kind == "task.exec":
            m.builtin(C, "rt_tasks_executed_total").inc()
            m.builtin(H, "rt_task_exec_s").observe(value)
        elif kind == "task.reply":
            m.builtin(C, "rt_task_replies_total").inc()
        elif kind == "task.retry":
            m.builtin(C, "rt_task_retries_total").inc()
        elif kind == "lease.grant":
            m.builtin(H, "rt_lease_latency_s",
                      boundaries=[0.001, 0.005, 0.02, 0.1, 0.5, 2, 10]
                      ).observe(value)
        elif kind == "rpc.frame":
            # One event covers attrs["frames"] frames (channel-side
            # aggregation); value is the triggering frame's latency.
            a = attrs or {}
            t = a.get("transport", "")
            m.builtin(H, "rt_rpc_frame_latency_s", tag_keys=("transport",),
                      boundaries=[0.0002, 0.001, 0.005, 0.02, 0.1, 1]
                      ).observe(value, tags={"transport": t})
            m.builtin(C, "rt_rpc_frames_total",
                      tag_keys=("transport",)).inc(
                a.get("frames", 1), tags={"transport": t})
            m.builtin(C, "rt_rpc_frame_bytes_total",
                      tag_keys=("transport",)).inc(
                a.get("bytes", 0), tags={"transport": t})
        elif kind == "pull.window":
            m.builtin(C, "rt_pull_windows_total").inc()
        elif kind == "pull.chunk":
            m.builtin(C, "rt_pull_bytes_total").inc(value)
        elif kind == "pull.failover":
            m.builtin(C, "rt_pull_failovers_total").inc()
        elif kind == "pull.shm_direct":
            m.builtin(C, "rt_pull_shm_direct_total").inc()
            m.builtin(C, "rt_pull_bytes_total").inc(value)
        elif kind == "push.chunk":
            m.builtin(C, "rt_push_bytes_total").inc(value)
        elif kind == "object.spill.write":
            m.builtin(C, "rt_spill_objects_total").inc()
            m.builtin(C, "rt_spill_bytes_total").inc(value)
        elif kind == "object.spill.restore":
            m.builtin(C, "rt_spill_restores_total").inc()
            m.builtin(C, "rt_spill_restore_bytes_total").inc(value)
        elif kind == "object.evict":
            m.builtin(C, "rt_evict_objects_total").inc()
            m.builtin(C, "rt_evict_bytes_total").inc(value)
        elif kind == "object.put.backpressure":
            m.builtin(C, "rt_put_backpressure_total").inc()
        elif kind == "object.array.put":
            m.builtin(C, "rt_array_puts_total").inc()
            m.builtin(C, "rt_array_put_bytes_total").inc(value)
        elif kind == "object.bcast.leg":
            m.builtin(C, "rt_bcast_legs_total").inc()
            m.builtin(C, "rt_bcast_bytes_total").inc(value)
        elif kind == "object.bcast.done":
            m.builtin(C, "rt_bcast_total").inc()
        elif kind == "object.bcast.fallback":
            m.builtin(C, "rt_bcast_fallback_total").inc(value or 1)
        elif kind == "inline.hit":
            m.builtin(C, "rt_inline_cache_hits_total").inc(value or 1)
        elif kind == "inline.miss":
            m.builtin(C, "rt_inline_cache_misses_total").inc(value or 1)
        elif kind == "inline.seal":
            m.builtin(C, "rt_inline_seals_total").inc(value)
        elif kind == "actor.window":
            m.builtin(m.Gauge, "rt_actor_push_window").set(value)
        elif kind == "fault.fired":
            m.builtin(C, "rt_faults_fired_total").inc()
        elif kind == "cgraph.execute":
            m.builtin(C, "rt_cgraph_executes_total").inc()
        elif kind == "cgraph.slot.write":
            m.builtin(C, "rt_cgraph_slot_writes_total").inc()
            m.builtin(H, "rt_cgraph_slot_write_s",
                      boundaries=[0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1]
                      ).observe(value)
        elif kind == "cgraph.slot.wait":
            m.builtin(H, "rt_cgraph_slot_wait_s",
                      boundaries=[0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1,
                                  1, 10]).observe(value)
        elif kind == "pipeline.stage.op":
            a = attrs or {}
            k = a.get("kind", "")
            m.builtin(C, "rt_pipeline_stage_ops_total",
                      tag_keys=("kind",)).inc(tags={"kind": k})
            m.builtin(H, "rt_pipeline_stage_op_s", tag_keys=("kind",),
                      boundaries=[0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2]
                      ).observe(value, tags={"kind": k})
        elif kind == "pipeline.step":
            m.builtin(C, "rt_pipeline_steps_total").inc()
            a = attrs or {}
            eff = a.get("efficiency")
            if eff is not None:
                m.builtin(m.Gauge, "rt_pipeline_efficiency").set(eff)
        elif kind == "serve.request":
            # value = request latency (s); attrs carry the HTTP code.
            a = attrs or {}
            code = str(a.get("code", ""))
            m.builtin(C, "rt_serve_requests_total",
                      tag_keys=("code",)).inc(tags={"code": code})
            m.builtin(H, "rt_serve_request_s",
                      boundaries=[0.001, 0.005, 0.02, 0.1, 0.5, 2, 10, 60]
                      ).observe(value)
        elif kind == "serve.shed":
            m.builtin(C, "rt_serve_shed_total").inc(value or 1)
        elif kind == "serve.timeout":
            m.builtin(C, "rt_serve_timeout_total").inc(value or 1)
        elif kind == "serve.retry":
            m.builtin(C, "rt_serve_retries_total").inc(value or 1)
        elif kind == "serve.drain":
            m.builtin(C, "rt_serve_drains_total").inc(value or 1)
        elif kind == "lock.cycle":
            m.builtin(C, "rt_lock_cycles_total").inc()
        elif kind == "lock.long_hold":
            m.builtin(C, "rt_lock_long_holds_total").inc()
        elif kind == "serve.batch.flush":
            # value = batch size; attrs carry the adaptive-window state.
            a = attrs or {}
            m.builtin(H, "rt_serve_batch_size",
                      boundaries=[1, 2, 4, 8, 16, 32, 64, 128]
                      ).observe(value)
            if a.get("window_ms") is not None:
                m.builtin(m.Gauge, "rt_serve_batch_window_ms").set(
                    a["window_ms"])
            if a.get("p99_ms") is not None:
                m.builtin(m.Gauge, "rt_serve_p99_ms").set(a["p99_ms"])
    if dropped:
        m.builtin(C, "rt_events_dropped_total").inc(dropped)


# ----------------------------------------------------------------------
# shipping
# ----------------------------------------------------------------------
def configure(node_id, conductor_address: str,
              start_flusher: bool = True) -> None:
    """Bind this process's ring to a cluster identity and (optionally)
    start the background flusher. Idempotent; a later call with
    start_flusher=True upgrades a piggyback-only process (head mode:
    daemon and driver share one process)."""
    global _node_hex, _conductor_addr, _flusher
    _node_hex = (node_id.hex() if isinstance(node_id, (bytes, bytearray))
                 else str(node_id))
    _conductor_addr = conductor_address
    from ray_tpu.util import metrics as _metrics
    _metrics.set_node(_node_hex)
    if not start_flusher:
        return
    with _flusher_lock:
        if _flusher is None or not _flusher.is_alive():
            _flush_stop.clear()
            _flusher = threading.Thread(target=_flush_loop, daemon=True,
                                        name="events-flush")
            _flusher.start()


def heartbeat_payload() -> Optional[dict]:
    """Drain for piggybacking on an already-periodic conductor RPC (the
    daemon heartbeat): None when there is nothing to ship."""
    global _unshipped, _unshipped_dropped
    evs, dropped = drain()
    if evs or dropped:
        try:
            _fold_metrics(evs, dropped)
        except Exception:
            pass
    with _ship_lock:
        if _unshipped or _unshipped_dropped:
            evs = _unshipped + evs
            dropped += _unshipped_dropped
            _unshipped, _unshipped_dropped = [], 0
    if not evs and not dropped:
        return None
    return {"pid": os.getpid(), "events": evs, "dropped": dropped}


def flush_now() -> None:
    """One flush pass: ship the ring delta + any buffered tracing spans
    to the conductor, fold metrics, sample probes."""
    global _unshipped, _unshipped_dropped
    addr = _conductor_addr
    if addr is None:
        return
    from ray_tpu.cluster.protocol import get_client
    cli = get_client(addr)
    evs, dropped = drain()
    if evs or dropped:
        try:
            _fold_metrics(evs, dropped)
        except Exception:
            pass
    with _ship_lock:
        if _unshipped or _unshipped_dropped:
            evs = _unshipped + evs
            dropped += _unshipped_dropped
            _unshipped, _unshipped_dropped = [], 0
    if evs or dropped:
        try:
            cli.call("push_ring_events", node_id=_node_hex, pid=os.getpid(),
                     events=evs, dropped=dropped)
        except Exception:
            with _ship_lock:
                keep = max(64, _cap or 16384)
                merged = evs + _unshipped
                _unshipped = merged[-keep:]
                _unshipped_dropped += dropped + max(0, len(merged) - keep)
            raise
    from ray_tpu.util import tracing
    if tracing.enabled():
        tracing.flush(cli)   # async replacement for the old inline flush
    _sample_probes()
    _check_slow_ops(cli)


def _flush_loop() -> None:
    while True:
        period = 0.5
        try:
            period = float(config.get("event_flush_period_s"))
        except Exception:
            pass
        if _flush_stop.wait(max(0.05, period)):
            return
        try:
            flush_now()
        except Exception:
            pass  # conductor down/restarting: next tick retries


def stop() -> None:
    """Stop the flusher (driver shutdown); best-effort final flush."""
    _flush_stop.set()
    try:
        flush_now()
    except Exception:
        pass


def reset_for_tests() -> None:
    """Forget ring + watchdog state (unit tests)."""
    global _buf, _cap, _seq, _cursor, _dropped, _enabled_gen
    global _watch_next, _unshipped, _unshipped_dropped
    _flush_stop.set()
    with _lock:
        _buf, _cap, _seq, _cursor, _dropped = [], 0, 0, 0, 0
        _enabled_gen = None
    with _ship_lock:
        _unshipped, _unshipped_dropped = [], 0
    with _watch_lock:
        _watch.clear()
        _watch_reported.clear()
        _watch_next = 0
    _scan_reported.clear()
