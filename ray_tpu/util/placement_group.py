"""Placement groups (parity: python/ray/util/placement_group.py:33/:136).

Bundles are reserved across node daemons with 2PC prepare/commit
(conductor.py, reference gcs_placement_group_scheduler.h:265). The TPU-first
strategy addition: STRICT_PACK on a TPU-labelled node keeps a whole pjit
gang on one ICI slice.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.exceptions import GetTimeoutError
from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
                    "SLICE")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str,
                 name: str = "", slice_topology: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name
        self.slice_topology = slice_topology

    def ready(self, timeout: Optional[float] = None):
        """Block until all bundles are reserved; returns self (the reference
        returns an ObjectRef — here readiness is a control-plane wait)."""
        from ray_tpu.core.api import _global_runtime
        rt = _global_runtime()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 5.0 if deadline is None else max(
                0.0, deadline - time.monotonic())
            info = rt.pg_ready(self.id.binary(), timeout=min(step, 5.0))
            if info["state"] == "CREATED":
                return self
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(
                    f"placement group {self.id.hex()} not ready "
                    f"(state={info['state']})")

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        try:
            self.ready(timeout=timeout_seconds)
            return True
        except GetTimeoutError:
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy,
                                 self.name, self.slice_topology))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK", name: str = "",
                    lifetime: Optional[str] = None,
                    slice_topology: str = "") -> PlacementGroup:
    """Reserve bundles across the cluster. strategy="SLICE" gang-places all
    bundles on the hosts of ONE ICI-connected TPU slice (bundle i on the
    slice's rank-i host); ``slice_topology`` ("v4-8") restricts which
    slices qualify."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}; "
                         f"one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be non-negative")
    from ray_tpu.core.api import _global_runtime
    rt = _global_runtime()
    pg_id = PlacementGroupID.from_random()
    rt.create_placement_group(pg_id.binary(), bundles, strategy, name,
                              slice_topology=slice_topology)
    return PlacementGroup(pg_id, bundles, strategy, name, slice_topology)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.api import _global_runtime
    _global_runtime().remove_placement_group(pg.id.binary())


def placement_group_table() -> List[dict]:
    from ray_tpu.core.api import _global_runtime
    rt = _global_runtime()
    return rt.conductor.call("list_placement_groups")
