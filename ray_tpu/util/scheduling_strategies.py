"""Scheduling strategies (parity: python/ray/util/scheduling_strategies.py:15).

TPU-first delta: SliceSchedulingStrategy pins a task/actor group to an
ICI-connected TPU slice (the placement group's bundles are slice-granular,
SURVEY.md §2a N9 mapping note) rather than to arbitrary nodes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "PlacementGroup"  # noqa: F821
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclass
class SliceSchedulingStrategy:
    """Gang-place onto one ICI slice: every bundle of the backing placement
    group maps to hosts of the same TPU slice so the pjit program's
    collectives ride ICI, not DCN."""
    topology: str = ""              # e.g. "v4-8"; "" = any slice
    placement_group: Optional["PlacementGroup"] = None  # noqa: F821
    placement_group_bundle_index: int = -1
