"""ActorPool (parity: python/ray/util/actor_pool.py) — round-robin a pool of
actors over a stream of work items with bounded in-flight submissions."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def map(self, fn: Callable, values: Iterable[Any]):
        """Ordered map: yields results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: float = None) -> Any:
        import ray_tpu as rt
        if self._next_return_index >= self._next_task_index and \
                not self._pending_submits:
            raise StopIteration("No more results to get")
        while self._next_return_index not in self._index_to_future:
            if not self.has_next():
                raise StopIteration("No more results to get")
            # drain a pending submit into flight
            if self._pending_submits and self._idle:
                self.submit(*self._pending_submits.pop(0))
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        try:
            return rt.get(future, timeout=timeout)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        import ray_tpu as rt
        if not self._future_to_actor:
            if not self._pending_submits:
                raise StopIteration("No more results to get")
        ready, _ = rt.wait(list(self._future_to_actor), num_returns=1,
                           timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        try:
            return rt.get(future)
        finally:
            self._return_actor(actor)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def pop_idle(self):
        return self._idle.pop() if self.has_free() else None

    def push(self, actor) -> None:
        self._return_actor(actor)
