"""Host-level collective groups for actors.

Role parity: python/ray/util/collective/collective.py:120-640 — declare a
collective group over N actors, then call allreduce/allgather/
reducescatter/broadcast/send/recv/barrier by group name. The reference
backs this with NCCL/GLOO; here the *device* data plane is XLA collectives
compiled into the step function (ray_tpu.parallel.collectives), so this
module only needs to cover the reference's CPU/GLOO role: host-side tensors
between actors, rendezvous'd through the conductor KV (the same role the
GCS internal KV plays for NCCL unique-id exchange, nccl_util.py).

Implementation: a fan-in/fan-out over the cluster KV — rank 0 reduces and
publishes, peers long-poll. O(N) per op; fine for control-plane-sized
payloads (weights broadcast rides the object store instead).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, List, Optional

import numpy as np

_NS = "collective"
_groups: Dict[str, "_Group"] = {}
_lock = threading.Lock()


class _Group:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.name = group_name
        self.seq = 0

    def _kv(self):
        from ray_tpu.core.api import _global_runtime
        return _global_runtime().conductor

    def _put(self, key: str, value: Any) -> None:
        self._kv().call("kv_put", ns=_NS, key=key.encode(),
                        value=pickle.dumps(value, protocol=5))

    def _get(self, key: str, timeout: float = 300.0) -> Any:
        blob = self._kv().call("kv_get", ns=_NS, key=key.encode(),
                               wait_timeout=timeout)
        if blob is None:
            raise TimeoutError(f"collective op timed out on key {key}")
        return pickle.loads(blob)

    def _del(self, key: str) -> None:
        self._kv().call("kv_del", ns=_NS, key=key.encode())

    # -- ops -----------------------------------------------------------
    def _fan_in_out(self, payload: Any, reduce_fn) -> Any:
        """All ranks publish; rank 0 reduces and publishes the result."""
        s = self.seq
        self.seq += 1
        base = f"{self.name}/{s}"
        self._put(f"{base}/in/{self.rank}", payload)
        if self.rank == 0:
            parts = [self._get(f"{base}/in/{r}")
                     for r in range(self.world_size)]
            out = reduce_fn(parts)
            self._put(f"{base}/out", out)
        result = self._get(f"{base}/out")
        # rank 0 lazily GCs the previous round's keys
        if self.rank == 0 and s >= 2:
            old = f"{self.name}/{s - 2}"
            for r in range(self.world_size):
                self._del(f"{old}/in/{r}")
            self._del(f"{old}/out")
        return result

    def allreduce(self, tensor, op: str = "SUM"):
        def red(parts):
            acc = np.asarray(parts[0]).copy()
            for p in parts[1:]:
                p = np.asarray(p)
                if op == "SUM" or op == "MEAN":
                    acc = acc + p
                elif op == "MAX":
                    acc = np.maximum(acc, p)
                elif op == "MIN":
                    acc = np.minimum(acc, p)
                elif op == "PRODUCT":
                    acc = acc * p
                else:
                    raise ValueError(f"unknown reduce op {op!r}")
            if op == "MEAN":
                acc = acc / len(parts)
            return acc
        return self._fan_in_out(np.asarray(tensor), red)

    def allgather(self, tensor) -> List[np.ndarray]:
        return self._fan_in_out(np.asarray(tensor),
                                lambda parts: [np.asarray(p) for p in parts])

    def reducescatter(self, tensor, op: str = "SUM") -> np.ndarray:
        summed = self.allreduce(tensor, op=op)
        chunks = np.array_split(summed, self.world_size)
        return chunks[self.rank]

    def broadcast(self, tensor, src_rank: int = 0) -> np.ndarray:
        s = self.seq
        self.seq += 1
        base = f"{self.name}/{s}"
        if self.rank == src_rank:
            self._put(f"{base}/out", np.asarray(tensor))
        return self._get(f"{base}/out")

    def barrier(self) -> None:
        self.allreduce(np.zeros(1))

    def send(self, tensor, dst_rank: int) -> None:
        s = self.seq
        self.seq += 1
        self._put(f"{self.name}/p2p/{s}/{self.rank}->{dst_rank}",
                  np.asarray(tensor))

    def recv(self, src_rank: int) -> np.ndarray:
        s = self.seq
        self.seq += 1
        key = f"{self.name}/p2p/{s}/{src_rank}->{self.rank}"
        out = self._get(key)
        self._del(key)
        return out


def init_collective_group(world_size: int, rank: int,
                          backend: str = "shm",
                          group_name: str = "default") -> None:
    """Called inside each participating actor (parity: collective.py:120)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
        _groups[group_name] = _Group(world_size, rank, group_name)
    # rendezvous barrier so all ranks exist before the first op
    _groups[group_name].barrier()


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int], backend: str = "shm",
                            group_name: str = "default"):
    """Declare a group externally over actor handles (collective.py:151).
    Each actor must expose an ``init_group(world_size, rank, backend, name)``
    method (convention used by the reference's examples)."""
    import ray_tpu as rt
    refs = [a.init_group.remote(world_size, r, backend, group_name)
            for a, r in zip(actors, ranks)]
    rt.get(refs)


def _group(group_name: str) -> _Group:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group() first")
    return g


def allreduce(tensor, group_name: str = "default", op: str = "SUM"):
    return _group(group_name).allreduce(tensor, op=op)


def allgather(tensor, group_name: str = "default"):
    return _group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "SUM"):
    return _group(group_name).reducescatter(tensor, op=op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank=src_rank)


def barrier(group_name: str = "default") -> None:
    _group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    _group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return _group(group_name).recv(src_rank)


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        _groups.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size
