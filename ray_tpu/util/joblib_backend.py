"""joblib parallel backend over ray_tpu tasks.

Role parity: python/ray/util/joblib (register_ray + RayBackend) — lets
scikit-learn-style `joblib.Parallel(...)` fan work out over the cluster by
selecting ``parallel_backend("ray_tpu")``. Each joblib batch becomes one
task; results stream back through ObjectRefs.
"""

from __future__ import annotations

from typing import Any, Callable


def register_ray_tpu() -> None:
    """Register the "ray_tpu" joblib backend (parity: register_ray()).

    Usage:
        import joblib
        from ray_tpu.util.joblib_backend import register_ray_tpu
        register_ray_tpu()
        with joblib.parallel_backend("ray_tpu"):
            Parallel(n_jobs=8)(delayed(f)(x) for x in xs)
    """
    try:
        from joblib._parallel_backends import MultiprocessingBackend
        from joblib.parallel import register_parallel_backend
    except ImportError as e:  # pragma: no cover - joblib is baked in
        raise ImportError(
            "joblib is required for the ray_tpu joblib backend") from e

    import ray_tpu

    @ray_tpu.remote
    def _joblib_batch(f):
        return f()

    from ray_tpu.core.exceptions import TaskError

    def _unwrap(exc: BaseException) -> BaseException:
        """Surface the ORIGINAL exception class to joblib callers (a
        sklearn user catching ValueError must not get our TaskError)."""
        return exc.cause if isinstance(exc, TaskError) else exc

    class _Result:
        def __init__(self, fut):
            self._fut = fut

        def get(self, timeout=None):
            try:
                return self._fut.result(timeout=timeout)
            except TaskError as e:
                raise _unwrap(e) from e

    class RayTpuBackend(MultiprocessingBackend):
        """Batches execute as ray_tpu tasks; the MultiprocessingBackend
        base supplies joblib's batching/auto-batch-size machinery (the
        reference's RayBackend subclasses it for the same reason) — but
        configure() must NOT build the base's local MemmappingPool (it
        would fork cluster-CPU-count idle processes on the driver)."""

        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            pass  # no local pool to tear down

        def effective_n_jobs(self, n_jobs: int) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            eager = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs == -1:
                return max(1, eager)
            return max(1, n_jobs)

        def apply_async(self, func: Callable[[], Any], callback=None):
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            ref = _joblib_batch.remote(func)
            fut = ray_tpu.core.api._ref_future(ref)
            if callback is not None:
                # joblib's completion callback must fire on error too (it
                # doubles as error_callback in the pool protocol) or the
                # dispatcher stalls waiting for the batch.
                fut.add_done_callback(
                    lambda f: callback(_unwrap(f.exception())
                                       if f.exception() else f.result()))
            return _Result(fut)

        def submit(self, func, callback=None):
            # joblib >= 1.5 entry point; older versions route through
            # apply_async directly.
            return self.apply_async(func, callback)

    register_parallel_backend("ray_tpu", RayTpuBackend)
