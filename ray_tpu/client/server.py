"""Client proxy server — remote drivers without a full runtime.

Role parity: python/ray/util/client/server/server.py (RayletServicer) — the
reference's "Ray client" runs a gRPC proxy inside the cluster; thin clients
(Python elsewhere, or other languages) drive the cluster through it. Here the
proxy wraps a full ClusterRuntime driver connection and exposes a small
simple-typed RPC surface over the standard frame protocol, so both the thin
Python client (ray_tpu/client/runtime.py, ``init("client://host:port")``)
and the C++ worker API (native/cppapi) can use it.

Sessions pin every ObjectRef/ActorHandle that crosses the boundary in a
per-session table (the cluster-side anchor for the distributed refcount,
reference role: util/client/server/server.py object ownership); clients
release ids explicitly (batched) and everything drops on disconnect.

Every RPC returns a plain dict ``{"ok": bool, ...}`` and never raises, so
non-Python clients only ever parse simple pickles; Python clients get the
original exception back via ``exc`` (pickled) for faithful re-raise.
"""

from __future__ import annotations

import os
import pickle
import threading
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.client import common
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.options import (ActorOptions, TaskOptions,
                                  make_actor_options, make_task_options)
from ray_tpu.core.refs import ObjectRef
from ray_tpu.core.task_spec import FunctionDescriptor
from ray_tpu.cluster.protocol import RpcServer


def _import_path(path: str):
    """Resolve "pkg.module:attr" (cross-language task/actor target)."""
    import importlib
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(f"import path {path!r} must be 'module:attr'")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


class _Session:
    def __init__(self, session_id: str, meta: dict):
        self.id = session_id
        self.meta = meta
        self.refs: Dict[bytes, ObjectRef] = {}
        self.actors: Dict[bytes, ActorHandle] = {}
        # submission_id -> cached response (or in-progress Event): makes
        # cp_put/cp_task/cp_actor_create/cp_actor_task idempotent under the
        # RPC layer's at-least-once delivery (a retried submission whose
        # reply was lost must not mint a second object / run the task
        # twice). The Event covers the race where the retry arrives while
        # the original is STILL EXECUTING: the duplicate blocks until the
        # first attempt's response is recorded. Bounded FIFO.
        self.seen: Dict[str, Any] = {}
        self._settled: "deque[str]" = deque()  # eviction order, O(1)
        self.lock = threading.Lock()

    def begin(self, submission_id: Optional[str]
              ) -> Tuple[Optional[dict], bool]:
        """-> (cached_response, is_owner). Owner executes and must record();
        a duplicate waits for the owner's response and replays it."""
        if submission_id is None:
            return None, True
        with self.lock:
            cur = self.seen.get(submission_id)
            if cur is None:
                self.seen[submission_id] = threading.Event()
                return None, True
        if isinstance(cur, threading.Event):
            cur.wait(timeout=600.0)
            with self.lock:
                cur = self.seen.get(submission_id)
            if isinstance(cur, threading.Event) or cur is None:
                return {"ok": False,
                        "error": "duplicate submission still in progress"}, \
                    False
        return cur, False

    def record(self, submission_id: Optional[str], resp: dict) -> dict:
        if submission_id is not None:
            with self.lock:
                prev = self.seen.get(submission_id)
                self.seen[submission_id] = resp
                self._settled.append(submission_id)
                # Evict oldest settled entries; pending Events are never in
                # _settled and so survive until their owner records.
                while len(self.seen) > 4096 and self._settled:
                    old = self._settled.popleft()
                    if not isinstance(self.seen.get(old), threading.Event):
                        self.seen.pop(old, None)
            if isinstance(prev, threading.Event):
                prev.set()
        return resp


class ClientProxy:
    """Serves ``rpc_cp_*`` methods; one instance per hosting driver."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self._rt = runtime
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._server = RpcServer(self, host=host, port=port)
        self.address = self._server.address

    def stop(self) -> None:
        with self._lock:
            sessions, self._sessions = dict(self._sessions), {}
        for s in sessions.values():
            with s.lock:
                s.refs.clear()
                s.actors.clear()
        self._server.stop()

    # -- session codec -----------------------------------------------------
    def _session(self, session: str) -> _Session:
        s = self._sessions.get(session)
        if s is None:
            raise KeyError(f"unknown client session {session!r}")
        return s

    def _enc(self, s: _Session, obj: Any) -> bytes:
        def pid(o):
            m = common.marker_for(o)
            if m is not None and m[0] == "ref":
                with s.lock:
                    s.refs.setdefault(m[1], o)   # pin for the client
            elif m is not None and m[0] == "actor":
                with s.lock:
                    s.actors.setdefault(m[1], o)
            return m
        return common.dumps(obj, pid)

    def _dec(self, s: _Session, blob: bytes) -> Any:
        def pload(pid):
            kind = pid[0]
            if kind == "ref":
                with s.lock:
                    ref = s.refs.get(pid[1])
                    if ref is None:
                        # Ref minted by another session/driver: materialize
                        # (registers with this driver's tracker) and pin.
                        ref = ObjectRef(ObjectID(pid[1]), owner=pid[2])
                        s.refs[pid[1]] = ref
                return ref
            if kind == "actor":
                with s.lock:
                    h = s.actors.get(pid[1])
                    if h is None:
                        h = ActorHandle(ActorID(pid[1]), pid[2], pid[3],
                                        pid[4])
                        s.actors[pid[1]] = h
                return h
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
        return common.loads(blob, pload)

    @staticmethod
    def _fail(e: BaseException) -> dict:
        try:
            exc = pickle.dumps(e, protocol=5)
        except Exception:
            exc = None
        return {"ok": False, "error": f"{type(e).__name__}: {e}",
                "traceback": traceback.format_exc(), "exc": exc}

    def _idempotent(self, session: str, submission_id: Optional[str],
                    body) -> dict:
        """Session lookup + begin/record dedupe around ``body(s) -> resp``.
        Failures are recorded too: a retried submission replays the
        original attempt's error instead of executing a second time."""
        try:
            s = self._session(session)
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)
        cached, owner = s.begin(submission_id)
        if not owner:
            return cached
        try:
            resp = body(s)
        except BaseException as e:  # noqa: BLE001
            resp = self._fail(e)
        return s.record(submission_id, resp)

    # -- lifecycle ---------------------------------------------------------
    def rpc_cp_connect(self, meta: Optional[dict] = None) -> dict:
        session_id = os.urandom(8).hex()
        with self._lock:
            self._sessions[session_id] = _Session(session_id, meta or {})
        return {"ok": True, "session": session_id,
                "address": getattr(self._rt, "address", None),
                "namespace": getattr(self._rt, "namespace", "")}

    def rpc_cp_disconnect(self, session: str) -> dict:
        with self._lock:
            s = self._sessions.pop(session, None)
        if s is not None:
            with s.lock:
                s.refs.clear()
                s.actors.clear()
        return {"ok": True}

    def rpc_cp_release(self, session: str, oids: List[bytes]) -> dict:
        try:
            s = self._session(session)
            with s.lock:
                for oid in oids:
                    s.refs.pop(oid, None)
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    # -- objects -----------------------------------------------------------
    def rpc_cp_put(self, session: str, blob: bytes,
                   put_id: Optional[str] = None) -> dict:
        def body(s):
            ref = self._rt.put(self._dec(s, blob))
            return {"ok": True, "ref": self._enc(s, ref)}
        return self._idempotent(session, put_id, body)

    def rpc_cp_get(self, session: str, oids: List[bytes],
                   timeout: Optional[float] = None) -> dict:
        try:
            s = self._session(session)
            with s.lock:
                refs = [s.refs.get(oid) or ObjectRef(ObjectID(oid))
                        for oid in oids]
            vals = self._rt.get(refs, timeout=timeout)
            return {"ok": True, "values": [self._enc(s, v) for v in vals]}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    def rpc_cp_wait(self, session: str, oids: List[bytes], num_returns: int,
                    timeout: Optional[float] = None) -> dict:
        try:
            s = self._session(session)
            with s.lock:
                refs = [s.refs.get(oid) or ObjectRef(ObjectID(oid))
                        for oid in oids]
            ready, rest = self._rt.wait(refs, num_returns=num_returns,
                                        timeout=timeout)
            return {"ok": True,
                    "ready": [r.id.binary() for r in ready],
                    "not_ready": [r.id.binary() for r in rest]}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    # -- tasks -------------------------------------------------------------
    def rpc_cp_task(self, session: str, desc: Optional[FunctionDescriptor],
                    blob: Optional[bytes], args_blob: bytes,
                    opts: Optional[dict] = None,
                    import_path: Optional[str] = None,
                    submission_id: Optional[str] = None) -> dict:
        def body(s):
            d, b = desc, blob
            if import_path is not None:
                fn = _import_path(import_path)
                d, b = FunctionDescriptor.for_callable(fn)
            topts = (opts if isinstance(opts, TaskOptions)
                     else make_task_options(None, **(opts or {})))
            args, kwargs = self._dec(s, args_blob)
            refs = self._rt.submit_task(d, b, args, kwargs, topts)
            return {"ok": True, "refs": self._enc(s, refs)}
        return self._idempotent(session, submission_id, body)

    # -- actors ------------------------------------------------------------
    def rpc_cp_actor_create(self, session: str,
                            desc: Optional[FunctionDescriptor],
                            blob: Optional[bytes], args_blob: bytes,
                            opts: Optional[dict] = None,
                            methods: Optional[dict] = None,
                            is_async: bool = False,
                            import_path: Optional[str] = None,
                            submission_id: Optional[str] = None) -> dict:
        def body(s):
            d, b, m, asy = desc, blob, methods, is_async
            if import_path is not None:
                cls = _import_path(import_path)
                d, b = FunctionDescriptor.for_callable(cls)
                m = ActorClass._scan_methods(cls)
                import inspect
                asy = any(inspect.iscoroutinefunction(getattr(cls, name))
                          for name in m)
            aopts = (opts if isinstance(opts, ActorOptions)
                     else make_actor_options(None, **(opts or {})))
            args, kwargs = self._dec(s, args_blob)
            handle = self._rt.create_actor(d, b, args, kwargs, aopts,
                                           m or {}, asy)
            return {"ok": True, "actor": self._enc(s, handle)}
        return self._idempotent(session, submission_id, body)

    def rpc_cp_actor_task(self, session: str, actor_id: bytes,
                          method_name: str, args_blob: bytes,
                          opts: Optional[dict] = None,
                          submission_id: Optional[str] = None) -> dict:
        def body(s):
            with s.lock:
                handle = s.actors.get(actor_id)
            if handle is None:
                raise ValueError(
                    f"actor {actor_id.hex()[:8]} not known to this session")
            topts = (opts if isinstance(opts, TaskOptions)
                     else make_task_options(None, **(opts or {})))
            args, kwargs = self._dec(s, args_blob)
            refs = self._rt.submit_actor_task(handle, method_name, args,
                                              kwargs, topts)
            return {"ok": True, "refs": self._enc(s, refs)}
        return self._idempotent(session, submission_id, body)

    def rpc_cp_actor_kill(self, session: str, actor_id: bytes,
                          no_restart: bool = True) -> dict:
        try:
            s = self._session(session)
            with s.lock:
                handle = s.actors.get(actor_id)
            if handle is None:
                handle = ActorHandle(ActorID(actor_id), "", {}, False)
            self._rt.kill_actor(handle, no_restart=no_restart)
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    def rpc_cp_get_actor(self, session: str, name: str,
                         namespace: str = "") -> dict:
        try:
            s = self._session(session)
            handle = self._rt.get_actor(name, namespace)
            return {"ok": True, "actor": self._enc(s, handle)}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    def rpc_cp_cancel(self, session: str, oid: bytes,
                      force: bool = False) -> dict:
        try:
            s = self._session(session)
            with s.lock:
                ref = s.refs.get(oid) or ObjectRef(ObjectID(oid))
            self._rt.cancel(ref, force=force)
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)

    # -- cluster introspection --------------------------------------------
    def rpc_cp_cluster_info(self, session: str, kind: str) -> dict:
        try:
            if kind == "nodes":
                return {"ok": True, "value": self._rt.nodes()}
            if kind == "cluster_resources":
                return {"ok": True, "value": self._rt.cluster_resources()}
            if kind == "available_resources":
                return {"ok": True, "value": self._rt.available_resources()}
            if kind == "timeline":
                return {"ok": True, "value": self._rt.timeline_events()}
            raise ValueError(f"unknown cluster_info kind {kind!r}")
        except BaseException as e:  # noqa: BLE001
            return self._fail(e)


def serve_proxy(address: Optional[str] = None, host: str = "127.0.0.1",
                port: int = 0) -> ClientProxy:
    """Start a proxy, connecting a driver runtime to ``address`` if this
    process hasn't already got one (CLI: ``ray_tpu client-server``)."""
    from ray_tpu.core import api
    if api.is_initialized():
        rt = api._global_runtime()
    else:
        rt = api.init(address=address)
    return ClientProxy(rt, host=host, port=port)
