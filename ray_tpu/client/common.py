"""Client⇄proxy value codec.

Role parity: python/ray/util/client/client_pickler.py — values crossing the
client boundary are pickled with persistent-id hooks so ObjectRefs and
ActorHandles travel as small markers instead of live runtime objects. The
proxy side resolves markers against (and registers new refs into) the
session's pin table, which is what keeps client-held objects alive in the
cluster's distributed refcount while the thin client holds only ids.

Marker forms (the persistent id tuples):
  ("ref", oid_bytes, owner_str_or_None)
  ("actor", actor_id_bytes, class_name, methods_dict, is_async)
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, Optional

from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.refs import ObjectRef


def dumps(obj: Any, persistent_id: Callable[[Any], Optional[tuple]]) -> bytes:
    buf = io.BytesIO()
    p = pickle.Pickler(buf, protocol=5)
    p.persistent_id = persistent_id  # type: ignore[assignment]
    p.dump(obj)
    return buf.getvalue()


def loads(data: bytes, persistent_load: Callable[[tuple], Any]) -> Any:
    up = pickle.Unpickler(io.BytesIO(data))
    up.persistent_load = persistent_load  # type: ignore[assignment]
    return up.load()


def marker_for(obj: Any) -> Optional[tuple]:
    """Shared persistent_id: handles → markers; everything else inline."""
    if isinstance(obj, ObjectRef):
        return ("ref", obj.id.binary(), obj.owner_address)
    if isinstance(obj, ActorHandle):
        return ("actor", obj.actor_id.binary(), obj._rt_class_name,
                obj._rt_method_options, obj._rt_is_async)
    return None


def handle_from_marker(pid: tuple) -> Any:
    """Shared persistent_load for processes with a live refs tracker: simply
    materialize the handle (ObjectRef.__init__ registers with the tracker)."""
    kind = pid[0]
    if kind == "ref":
        return ObjectRef(ObjectID(pid[1]), owner=pid[2])
    if kind == "actor":
        return ActorHandle(ActorID(pid[1]), pid[2], pid[3], pid[4])
    raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
