"""Client proxy: thin drivers over an in-cluster proxy.

Role parity: python/ray/util/client — ``ray.init("ray://...")``. Here:
``ray_tpu.init(address="client://host:port")`` (thin Python client), the
C++ worker API (native/cppapi) speaks the same proxy protocol.
"""

from ray_tpu.client.runtime import ClientRuntime
from ray_tpu.client.server import ClientProxy, serve_proxy

__all__ = ["ClientProxy", "ClientRuntime", "serve_proxy"]
