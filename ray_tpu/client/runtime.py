"""Thin client runtime — ``init("client://host:port")``.

Role parity: python/ray/util/client/worker.py (Worker) + api.py — a driver
that holds NO cluster runtime: every operation is an RPC to a ClientProxy
(ray_tpu/client/server.py) running inside the cluster. The full public API
(@remote, .remote(), get/put/wait, actors) works unchanged because this class
implements the same runtime interface ClusterRuntime does.

Ref lifetime: the proxy pins every ref that crosses the boundary in the
session table. Client-side, a lightweight tracker counts live ObjectRef
handles per oid and batches release RPCs when the last local handle drops —
the client half of the distributed refcount (reference role:
util/client/common.py ClientObjectRef __del__ → ReleaseObject).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.client import common
from ray_tpu.core import refs as refs_mod
from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.refs import ObjectRef
from ray_tpu.cluster import protocol


class _ClientRefTracker:
    """Counts live local handles; ships batched releases to the proxy."""

    def __init__(self, release_fn):
        self._release = release_fn
        self._counts: Dict[bytes, int] = {}
        self._pending: List[bytes] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="client-ref-flush")
        self._thread.start()

    def handle_created(self, oid: bytes) -> None:
        with self._lock:
            self._counts[oid] = self._counts.get(oid, 0) + 1

    def handle_dropped(self, oid: bytes) -> None:
        with self._lock:
            n = self._counts.get(oid, 0) - 1
            if n > 0:
                self._counts[oid] = n
            else:
                self._counts.pop(oid, None)
                self._pending.append(oid)

    def _drain(self) -> List[bytes]:
        with self._lock:
            batch, self._pending = self._pending, []
        return batch

    def _flush_loop(self) -> None:
        while not self._stop.wait(0.2):
            batch = self._drain()
            if batch:
                try:
                    self._release(batch)
                except Exception:
                    pass  # proxy gone; disconnect cleans up server-side

    def stop(self) -> None:
        self._stop.set()


class ClientRuntime:
    """Runtime-interface implementation over the client proxy protocol."""

    def __init__(self, address: str, namespace: Optional[str] = None):
        if refs_mod._tracker is not None:
            # Checked BEFORE cp_connect so a refused init doesn't leak a
            # never-disconnected proxy session.
            raise RuntimeError(
                "client runtime cannot coexist with a cluster runtime "
                "in one process")
        if address.startswith("client://"):
            address = address[len("client://"):]
        self._proxy_addr = address
        self._client = protocol.RpcClient(address, reconnect_s=5.0)
        resp = self._client.call("cp_connect", meta={"namespace": namespace})
        if not resp.get("ok"):
            raise ConnectionError(resp.get("error", "client connect failed"))
        self._session = resp["session"]
        self.address = resp.get("address") or address
        self.namespace = namespace or resp.get("namespace") or ""
        self.job_id = f"client-{self._session}"
        self.node_id = None
        self._shutdown = False
        self._tracker = _ClientRefTracker(self._release)
        refs_mod._tracker = self._tracker

    # -- plumbing ----------------------------------------------------------
    def _call(self, method: str, **kwargs) -> dict:
        resp = self._client.call(method, session=self._session, **kwargs)
        if resp.get("ok"):
            return resp
        exc = resp.get("exc")
        if exc is not None:
            import pickle
            try:
                # Unpickling can fail for cluster-only exception classes
                # (ModuleNotFoundError etc.) — fall back to the error string.
                e = pickle.loads(exc)
            except Exception:
                e = None
            if isinstance(e, BaseException):
                raise e
        raise protocol.RpcError(resp.get("error", "client call failed"))

    def _release(self, oids: List[bytes]) -> None:
        if not self._shutdown:
            self._client.call("cp_release", session=self._session, oids=oids)

    def _enc(self, obj: Any) -> bytes:
        return common.dumps(obj, common.marker_for)

    def _dec(self, blob: bytes) -> Any:
        return common.loads(blob, common.handle_from_marker)

    @staticmethod
    def _sid() -> str:
        """Fresh submission id: lets the proxy dedupe a resend of the same
        logical call (at-least-once RPC delivery) without double-executing."""
        import os as _os
        return _os.urandom(8).hex()

    # -- objects -----------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        return self._dec(self._call("cp_put", blob=self._enc(value),
                                    put_id=self._sid())["ref"])

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id.binary() for r in refs]
        resp = self._call("cp_get", oids=oids, timeout=timeout,
                          _timeout=None if timeout is None else timeout + 30)
        return [self._dec(b) for b in resp["values"]]

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float] = None
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        by_oid = {r.id.binary(): r for r in refs}
        resp = self._call("cp_wait", oids=list(by_oid), num_returns=num_returns,
                          timeout=timeout)
        return ([by_oid[o] for o in resp["ready"]],
                [by_oid[o] for o in resp["not_ready"]])

    # -- tasks / actors ----------------------------------------------------
    def submit_task(self, desc, blob, args, kwargs, opts) -> List[ObjectRef]:
        resp = self._call("cp_task", desc=desc, blob=blob,
                          args_blob=self._enc((list(args), dict(kwargs))),
                          opts=opts, submission_id=self._sid())
        return self._dec(resp["refs"])

    def create_actor(self, desc, blob, args, kwargs, opts, methods,
                     is_async) -> ActorHandle:
        resp = self._call("cp_actor_create", desc=desc, blob=blob,
                          args_blob=self._enc((list(args), dict(kwargs))),
                          opts=opts, methods=methods, is_async=is_async,
                          submission_id=self._sid())
        return self._dec(resp["actor"])

    def submit_actor_task(self, handle: ActorHandle, method_name: str, args,
                          kwargs, opts) -> List[ObjectRef]:
        resp = self._call("cp_actor_task",
                          actor_id=handle._rt_actor_id.binary(),
                          method_name=method_name,
                          args_blob=self._enc((list(args), dict(kwargs))),
                          opts=opts, submission_id=self._sid())
        return self._dec(resp["refs"])

    def kill_actor(self, handle: ActorHandle, no_restart: bool = True) -> None:
        self._call("cp_actor_kill", actor_id=handle._rt_actor_id.binary(),
                   no_restart=no_restart)

    def get_actor(self, name: str, namespace: str = "") -> ActorHandle:
        resp = self._call("cp_get_actor", name=name,
                          namespace=namespace or self.namespace)
        return self._dec(resp["actor"])

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._call("cp_cancel", oid=ref.id.binary(), force=force)

    # -- introspection -----------------------------------------------------
    def nodes(self) -> List[dict]:
        return self._call("cp_cluster_info", kind="nodes")["value"]

    def cluster_resources(self) -> Dict[str, float]:
        return self._call("cp_cluster_info", kind="cluster_resources")["value"]

    def available_resources(self) -> Dict[str, float]:
        return self._call("cp_cluster_info",
                          kind="available_resources")["value"]

    def timeline_events(self) -> List[dict]:
        return self._call("cp_cluster_info", kind="timeline")["value"]

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        if refs_mod._tracker is self._tracker:
            refs_mod._tracker = None
        self._tracker.stop()
        # Final synchronous release so the proxy drops pins promptly.
        batch = self._tracker._drain()
        try:
            if batch:
                self._client.call("cp_release", session=self._session,
                                  oids=batch)
            self._client.call("cp_disconnect", session=self._session)
        except Exception:
            pass
        self._client.close()
        time.sleep(0)  # let the flusher observe _stop
