"""Dashboard: HTTP UI + JSON API over the cluster's state.

Role parity: dashboard/head.py:71 (the head-side dashboard server: REST
endpoints for nodes/actors/jobs + static UI) — re-scoped TPU-first: no
React bundle or per-node agent processes (the node daemon already serves
the per-node surface the reference's dashboard agent provides,
dashboard/agent.py:66), just a dependency-free threaded HTTP server the
head starts next to the conductor.

Endpoints:
    /                  one-page HTML overview (auto-refreshing)
    /api/cluster       totals + per-node resources
    /api/nodes         node table
    /api/actors        actor table
    /api/jobs          job table (submission records from the KV)
    /api/tasks         recent task events
    /api/placement_groups
    /api/objects       per-node object-store stats
    /metrics           Prometheus text (util/metrics.py exposition)
"""

from __future__ import annotations

import json
import pickle
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ray_tpu.cluster.protocol import get_client

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa}
h1{font-size:20px} h2{font-size:15px;margin-top:28px}
table{border-collapse:collapse;font-size:13px;min-width:480px}
td,th{border:1px solid #ddd;padding:4px 10px;text-align:left}
th{background:#f0f0f0} .ALIVE{color:#0a7d32} .DEAD,.FAILED{color:#b00020}
</style></head><body>
<h1>ray_tpu cluster</h1><div id=c>loading…</div>
<script>
async function j(p){return (await fetch(p)).json()}
(async()=>{
 const [cl,no,ac,jo,dbg]=await Promise.all(
   [j('/api/cluster'),j('/api/nodes'),j('/api/actors'),j('/api/jobs'),
    j('/api/debug').catch(()=>({nodes:{}}))]);
 let h=`<h2>Resources</h2><table><tr><th>resource</th><th>available</th>
 <th>total</th></tr>`;
 for(const k of Object.keys(cl.total))
   h+=`<tr><td>${k}</td><td>${cl.available[k]??0}</td>
   <td>${cl.total[k]}</td></tr>`;
 h+=`</table><h2>Nodes (${no.length})</h2><table><tr><th>node</th>
 <th>state</th><th>head</th><th>address</th><th>resources</th>
 <th>debug</th><th>workers (profile)</th></tr>`;
 for(const n of no){
   const d=(dbg.nodes||{})[n.node_id]||{};
   const pids=(d.worker_pids||[]).map(p=>
     `<a href=/api/profile/${n.node_id}/${p}?duration=2>${p}</a>`).join(' ');
   h+=`<tr><td>${n.node_id.slice(0,12)}</td>
 <td class=${n.state}>${n.state}</td><td>${n.is_head_node?'✓':''}</td>
 <td>${n.address}</td><td>${JSON.stringify(n.resources_total)}</td>
 <td><a href=/api/debug/${n.node_id}>state</a></td><td>${pids}</td></tr>`;}
 h+=`</table><h2>Actors (${ac.length})</h2><table><tr><th>actor</th>
 <th>class</th><th>name</th><th>state</th><th>restarts</th></tr>`;
 for(const a of ac) h+=`<tr><td>${a.actor_id.slice(0,12)}</td>
 <td>${a.class_name}</td><td>${a.name||''}</td>
 <td class=${a.state}>${a.state}</td><td>${a.num_restarts}</td></tr>`;
 h+=`</table><h2>Jobs (${jo.length})</h2><table><tr><th>id</th>
 <th>status</th><th>entrypoint</th></tr>`;
 for(const x of jo) h+=`<tr><td>${x.submission_id}</td>
 <td class=${x.status}>${x.status}</td><td>${x.entrypoint}</td></tr>`;
 document.getElementById('c').innerHTML=h+'</table>';
})();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # silence per-request stderr noise
        pass

    def _send(self, body: bytes, ctype: str = "application/json",
              code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj: Any) -> None:
        self._send(json.dumps(obj, default=str).encode())

    def do_GET(self):  # noqa: N802 - http.server API
        dash: "Dashboard" = self.server.dashboard  # type: ignore[attr-defined]
        try:
            path = self.path.split("?")[0].rstrip("/") or "/"
            if path == "/":
                self._send(_PAGE.encode(), "text/html")
            elif path == "/api/cluster":
                self._json(dash.cluster())
            elif path == "/api/nodes":
                self._json(dash.nodes())
            elif path == "/api/actors":
                self._json(dash.actors())
            elif path == "/api/jobs":
                self._json(dash.jobs())
            elif path == "/api/tasks":
                self._json(dash.tasks())
            elif path == "/api/placement_groups":
                self._json(dash.placement_groups())
            elif path == "/api/objects":
                self._json(dash.objects())
            elif path == "/api/events":
                self._json(dash.events())
            elif path == "/api/spans":
                self._json(dash.spans())
            elif path == "/api/ring":
                self._json(dash.ring())
            elif path == "/api/debug":
                self._json(dash.debug())
            elif path.startswith("/api/debug/"):
                # /api/debug/<node_hex> -> that node's daemon debug_state
                self._json(dash.debug(path.rsplit("/", 1)[-1]))
            elif path.startswith("/api/profile/"):
                # /api/profile/<pid>?duration=2            (any node)
                # /api/profile/<node_hex>/<pid>?duration=2 (scoped)
                from urllib.parse import parse_qs, urlparse
                q = parse_qs(urlparse(self.path).query)
                dur = float(q.get("duration", ["2.0"])[0])
                seg = path[len("/api/profile/"):].split("/")
                node_hex = seg[0] if len(seg) > 1 else None
                self._send(dash.profile(int(seg[-1]), dur,
                                        node_hex=node_hex).encode(),
                           "text/plain")
            elif path == "/metrics":
                from ray_tpu.util.metrics import prometheus_text
                self._send(prometheus_text().encode(), "text/plain")
            else:
                self._send(b'{"error": "not found"}', code=404)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 - surfaced as a 500
            try:
                self._send(json.dumps({"error": repr(e)}).encode(), code=500)
            except OSError:
                pass


class Dashboard:
    """Serves the UI/API backed by conductor + daemon RPCs."""

    def __init__(self, conductor_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._cli = get_client(conductor_address)
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.dashboard = self  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        threading.Thread(target=self._srv.serve_forever, daemon=True,
                         name="dashboard").start()

    # -- data providers -------------------------------------------------
    def cluster(self) -> dict:
        return {"total": self._cli.call("cluster_resources"),
                "available": self._cli.call("available_resources")}

    def nodes(self) -> list:
        return [{
            "node_id": n["node_id"].hex(),
            "state": "ALIVE" if n["alive"] else "DEAD",
            "is_head_node": n["is_head"],
            "address": n["address"],
            "resources_total": n["resources_total"],
            "resources_available": n["resources_available"],
        } for n in self._cli.call("get_nodes")]

    def actors(self) -> list:
        return self._cli.call("list_actors")

    def jobs(self) -> list:
        out = []
        for key in self._cli.call("kv_keys", ns="_jobs"):
            blob = self._cli.call("kv_get", ns="_jobs", key=key)
            if blob is not None:
                out.append(pickle.loads(blob))
        return sorted(out, key=lambda r: r.get("submit_time", 0))

    def tasks(self, limit: int = 500) -> list:
        return self._cli.call("get_task_events")[-limit:]

    def placement_groups(self) -> list:
        return self._cli.call("list_placement_groups")

    def events(self, limit: int = 500) -> list:
        return self._cli.call("list_events", limit=limit)

    def spans(self) -> list:
        # Spans ship via the background event flusher; flush this
        # process's tail first so a head-side dashboard read sees its
        # own just-recorded spans (read-your-writes, timeline() parity).
        try:
            from ray_tpu.util import events as _events
            _events.flush_now()
        except Exception:
            pass
        return self._cli.call("get_spans")

    def profile(self, pid: int, duration_s: float = 2.0,
                node_hex: Optional[str] = None) -> str:
        """Collapsed-stack profile of the worker with this OS pid.
        ``node_hex`` (a node-id hex prefix) scopes the probe to one node:
        pids are per-host, so on a multi-host cluster an unscoped probe
        can profile a DIFFERENT node's coincidentally-same pid."""
        for n in self._cli.call("get_nodes"):
            if not n["alive"]:
                continue
            if node_hex and not n["node_id"].hex().startswith(node_hex):
                continue
            try:
                dump = get_client(n["address"]).call(
                    "profile_worker", pid=pid, duration_s=duration_s,
                    _timeout=duration_s + 60.0)
            except Exception:
                continue
            if dump is not None:
                return dump
        where = f" on node {node_hex}" if node_hex else ""
        return f"no live worker with pid {pid}{where}"

    def ring(self, limit: int = 1000) -> list:
        """Recent flight-recorder events (conductor ring store)."""
        return self._cli.call("get_ring_events", limit=limit)

    def debug(self, node_hex: Optional[str] = None) -> dict:
        """Cluster debug-state dump (debug_state.txt role): conductor
        tables plus per-node daemon tables; ``node_hex`` narrows to one
        node's daemon."""
        nodes = self._cli.call("get_nodes")
        if node_hex:
            for n in nodes:
                if n["node_id"].hex().startswith(node_hex):
                    if not n["alive"]:
                        return {"error": f"node {node_hex} is dead"}
                    return get_client(n["address"]).call("debug_state")
            return {"error": f"no such node {node_hex}"}
        out = {"conductor": self._cli.call("debug_state"), "nodes": {}}
        for n in nodes:
            if not n["alive"]:
                continue
            hexid = n["node_id"].hex()
            try:
                out["nodes"][hexid] = get_client(
                    n["address"]).call("debug_state")
            except Exception as e:  # noqa: BLE001 - per-node best effort
                out["nodes"][hexid] = {"error": repr(e)}
        return out

    def objects(self) -> list:
        out = []
        for n in self._cli.call("get_nodes"):
            if not n["alive"]:
                continue
            try:
                stats = get_client(n["address"]).call("store_stats")
            except Exception:
                continue
            out.append({"node_id": n["node_id"].hex(), **stats})
        return out

    def stop(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass
