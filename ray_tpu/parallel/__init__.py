"""Parallelism strategies, TPU-first.

The reference ships DP/FSDP via torch process groups (reference
python/ray/train/torch/config.py:113, train_loop_utils.py:23-96) and has no
in-tree TP/PP/SP/EP (SURVEY.md §2d). Here every strategy is an axis of one
`jax.sharding.Mesh`:

    dp    data parallel          (batch sharded, grads psum'd by XLA)
    fsdp  sharded data parallel  (batch + params/optimizer sharded, ZeRO-3)
    tp    tensor parallel        (weight matrices sharded within a layer)
    pp    pipeline parallel      (layer stages; microbatched shard_map loop)
    sp    sequence/context par.  (ring attention / Ulysses over ICI)
    ep    expert parallel        (MoE experts sharded)

Shardings are expressed as logical-axis rules mapped onto mesh axes
(`LogicalRules`), compiled by pjit/GSPMD; collectives ride ICI.
"""

from ray_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    local_mesh,
    mesh_shape_for,
)
from ray_tpu.parallel.sharding import (
    LogicalRules,
    DEFAULT_RULES,
    logical_sharding,
    shard_pytree,
    with_sharding,
    batch_sharding,
    replicated,
)

__all__ = [
    "MeshSpec", "build_mesh", "local_mesh", "mesh_shape_for",
    "LogicalRules", "DEFAULT_RULES", "logical_sharding", "shard_pytree",
    "with_sharding", "batch_sharding", "replicated",
]
