"""In-program collectives: thin, named wrappers over XLA collectives.

The reference's data-plane collectives are NCCL/GLOO groups driven from
Python per-op (reference python/ray/util/collective/collective.py:258-640);
on TPU the equivalents are *compiled into the step function* and ride ICI.
These helpers are meant for use inside `shard_map`-ped functions where mesh
axes are visible as named axes. The host-level, actor-to-actor collective
API with the reference's signatures lives in ray_tpu.util.collective.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops import _compat

AxisName = Union[str, Sequence[str]]


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis_name=axis)


def allreduce_max(x, axis: AxisName):
    return lax.pmax(x, axis_name=axis)


def allreduce_min(x, axis: AxisName):
    return lax.pmin(x, axis_name=axis)


def allgather(x, axis: AxisName, *, concat_dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, axis=concat_dim, tiled=tiled)


def reducescatter(x, axis: AxisName, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dim,
                            tiled=True)


def alltoall(x, axis: AxisName, *, split_dim: int, concat_dim: int):
    return lax.all_to_all(x, axis_name=axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send to (i+shift) mod n along `axis` — the ICI-neighbor hop used by
    ring attention and pipeline stages."""
    n = _compat.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def broadcast_from(x, axis: str, *, root: int = 0):
    """Every member gets root's value (select-and-psum, compiles to an ICI
    broadcast)."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name=axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return _compat.axis_size(axis)


def broadcast_rounds(n: int, *, fanout: int = 2, root: int = 0):
    """Host-level broadcast schedule: rounds of (src, dst) legs spreading
    one copy from ``root`` to all ``n`` members, each holder re-sending to
    up to ``fanout`` new members per round (binomial tree at fanout=2, so
    ceil(log2 n) rounds instead of the n-1 serial pulls of the classic
    path). Pure schedule — the object plane drives the legs over the r08
    pipelined RPC layer (the CPU-host, gloo-style stand-in for an ICI
    collective; reference python/ray/util/collective gloo backend role).

    Members are 0..n-1; legs inside a round are independent and may run
    concurrently. A failed leg is the caller's problem (it re-stripes the
    missing member onto the classic pull path).
    """
    if n <= 0:
        return []
    if fanout < 1:
        fanout = 1
    have = [root % n]
    pending = [i for i in range(n) if i != root % n]
    rounds = []
    while pending:
        legs = []
        senders = list(have)
        for src in senders:
            for _ in range(fanout):
                if not pending:
                    break
                dst = pending.pop(0)
                legs.append((src, dst))
                have.append(dst)
        rounds.append(legs)
    return rounds
