"""In-program collectives: thin, named wrappers over XLA collectives.

The reference's data-plane collectives are NCCL/GLOO groups driven from
Python per-op (reference python/ray/util/collective/collective.py:258-640);
on TPU the equivalents are *compiled into the step function* and ride ICI.
These helpers are meant for use inside `shard_map`-ped functions where mesh
axes are visible as named axes. The host-level, actor-to-actor collective
API with the reference's signatures lives in ray_tpu.util.collective.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops import _compat

AxisName = Union[str, Sequence[str]]


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis_name=axis)


def allreduce_max(x, axis: AxisName):
    return lax.pmax(x, axis_name=axis)


def allreduce_min(x, axis: AxisName):
    return lax.pmin(x, axis_name=axis)


def allgather(x, axis: AxisName, *, concat_dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, axis=concat_dim, tiled=tiled)


def reducescatter(x, axis: AxisName, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dim,
                            tiled=True)


def alltoall(x, axis: AxisName, *, split_dim: int, concat_dim: int):
    return lax.all_to_all(x, axis_name=axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send to (i+shift) mod n along `axis` — the ICI-neighbor hop used by
    ring attention and pipeline stages."""
    n = _compat.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def broadcast_from(x, axis: str, *, root: int = 0):
    """Every member gets root's value (select-and-psum, compiles to an ICI
    broadcast)."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name=axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return _compat.axis_size(axis)
