"""Logical-axis sharding rules (GSPMD style).

Model code names its array dimensions with *logical* axes ("batch", "seq",
"embed", "mlp", "heads", "kv", "vocab", "layers", "expert"); a `LogicalRules`
table maps each logical axis to zero or more mesh axes. pjit + XLA insert the
collectives. This replaces both the reference's DDP wrapper (reference
python/ray/train/torch/train_loop_utils.py:92) and its FSDP delegation
(`parallel_strategy="fsdp"`, same file:23-96) with one declarative mechanism
that also covers TP/SP/EP, which the reference lacks in-tree (SURVEY.md §2d).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[str, Tuple[str, ...], None]


class LogicalRules:
    """Ordered mapping logical axis -> mesh axis (or tuple of mesh axes)."""

    def __init__(self, rules: Dict[str, MeshAxes]):
        self._rules = dict(rules)

    def get(self, logical: Optional[str]) -> MeshAxes:
        if logical is None:
            return None
        return self._rules.get(logical)

    def extend(self, extra: Dict[str, MeshAxes]) -> "LogicalRules":
        new = dict(self._rules)
        new.update(extra)
        return LogicalRules(new)

    def spec(self, logical_axes: Sequence[Optional[str]],
             mesh: Optional[Mesh] = None) -> P:
        """PartitionSpec for an array whose dims carry these logical names.

        Mesh axes of size 1 (strategy off) are dropped so the same model code
        compiles on any MeshSpec.
        """
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape)) if mesh else None
        out = []
        for ax in logical_axes:
            m = self.get(ax)
            if m is None:
                out.append(None)
                continue
            axes = (m,) if isinstance(m, str) else tuple(m)
            if sizes is not None:
                axes = tuple(a for a in axes if sizes.get(a, 1) > 1)
            if not axes:
                out.append(None)
            elif len(axes) == 1:
                out.append(axes[0])
            else:
                out.append(axes)
        while out and out[-1] is None:
            out.pop()
        return P(*out)


# The canonical transformer ruleset. batch over (dp, fsdp); weights sharded
# on fsdp along their largest dim (ZeRO-3); tp splits heads/mlp/vocab;
# sp shards the sequence dim; ep shards experts.
DEFAULT_RULES = LogicalRules({
    "batch": ("dcn_dp", "dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "expert": "ep",
    "expert_mlp": "tp",
    "stage": "pp",
})


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                     rules: LogicalRules = DEFAULT_RULES) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes, mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 2,
                   rules: LogicalRules = DEFAULT_RULES) -> NamedSharding:
    """Sharding for a [batch, ...] array: batch dim over the data axes."""
    return logical_sharding(mesh, ["batch"] + [None] * (ndim - 1), rules)


def shard_pytree(tree: Any, mesh: Mesh,
                 logical_axes_tree: Any,
                 rules: LogicalRules = DEFAULT_RULES) -> Any:
    """device_put a pytree of arrays with per-leaf logical axis names.

    `logical_axes_tree` mirrors `tree`, each leaf a tuple of logical names
    (or None) per dim.
    """
    def place(x, axes):
        sh = logical_sharding(mesh, axes, rules) if axes is not None \
            else replicated(mesh)
        return jax.device_put(x, sh)
    return jax.tree.map(place, tree, logical_axes_tree,
                        is_leaf=lambda x: x is None)


def with_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]], x,
                  rules: LogicalRules = DEFAULT_RULES):
    """In-jit sharding constraint (lax.with_sharding_constraint)."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules))


def pytree_shardings(tree: Any, mesh: Mesh, logical_axes_tree: Any,
                     rules: LogicalRules = DEFAULT_RULES) -> Any:
    """NamedShardings mirroring `tree` (for jit in_shardings/out_shardings)."""
    def mk(_, axes):
        return (logical_sharding(mesh, axes, rules) if axes is not None
                else replicated(mesh))
    return jax.tree.map(mk, tree, logical_axes_tree,
                        is_leaf=lambda x: x is None)
