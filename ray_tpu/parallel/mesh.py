"""Device-mesh construction over TPU slices.

One `jax.sharding.Mesh` with named axes ("dp","fsdp","tp","pp","sp","ep") is
the substrate of every parallelism strategy. The reference's analog is the
torch process-group bootstrap (reference python/ray/train/torch/config.py:113
dist.init_process_group); here there is no rendezvous per-strategy — you pick
axis sizes once and XLA compiles the collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dcn_dp", "pp", "dp", "fsdp", "sp", "ep", "tp")
# tp innermost: tensor-parallel collectives are per-layer and latency-bound,
# so tp must map to the fastest (most-adjacent) ICI dimension. pp outermost
# within a slice: stage-to-stage transfers happen once per microbatch.
# dcn_dp outermost of all: it is the ONLY axis allowed to cross slice
# boundaries — pure data parallelism between slices, so the sole
# inter-slice collective is the once-per-step gradient all-reduce, which is
# the one communication pattern that tolerates DCN latency (multislice
# recipe; the reference's nearest analog is multi-node NCCL DDP,
# reference python/ray/train/torch/config.py:113).


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis sizes for the global device mesh. 1 = strategy off.

    ``dcn_dp`` > 1 spans multiple TPU slices over DCN; all other axes must
    fit within one slice (their collectives ride ICI).
    """
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    dcn_dp: int = 1

    @property
    def num_devices(self) -> int:
        return (self.dp * self.fsdp * self.tp * self.pp * self.sp *
                self.ep * self.dcn_dp)

    @property
    def devices_per_slice(self) -> int:
        return self.num_devices // self.dcn_dp

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def active_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if getattr(self, a) > 1)

    @staticmethod
    def auto(num_devices: int, *, tp: int = 1, pp: int = 1, sp: int = 1,
             ep: int = 1, fsdp: Optional[int] = None,
             dcn_dp: int = 1) -> "MeshSpec":
        """Fill the remaining devices with (fsdp or dp) parallelism."""
        model = tp * pp * sp * ep * dcn_dp
        if num_devices % model:
            raise ValueError(
                f"tp*pp*sp*ep*dcn_dp={model} does not divide "
                f"num_devices={num_devices}")
        rest = num_devices // model
        if fsdp is None:
            return MeshSpec(dp=rest, tp=tp, pp=pp, sp=sp, ep=ep,
                            dcn_dp=dcn_dp)
        if rest % fsdp:
            raise ValueError(f"fsdp={fsdp} does not divide remainder {rest}")
        return MeshSpec(dp=rest // fsdp, fsdp=fsdp, tp=tp, pp=pp, sp=sp,
                        ep=ep, dcn_dp=dcn_dp)


def mesh_shape_for(spec: MeshSpec) -> Tuple[Tuple[str, int], ...]:
    return tuple((a, getattr(spec, a)) for a in AXIS_ORDER)


def _snake_iter(dims: Sequence[int]):
    """Yield every index of a grid of shape `dims` along a Hamiltonian path
    where consecutive indices differ by exactly 1 in exactly one dimension
    (generalized boustrophedon). dims[0] is the fastest-varying dimension.

    This is the adjacency guarantee the mesh builder rides on: a logical
    axis laid over K consecutive path positions occupies K chips connected
    by a chain of single-hop ICI links.
    """
    ndim = len(dims)
    total = 1
    for s in dims:
        total *= s
    for n in range(total):
        digits = []
        rem = n
        for size in dims:
            digits.append(rem % size)
            rem //= size
        # A dimension's direction reverses whenever the combined position of
        # all more-significant dimensions has odd parity, so every carry
        # into a higher digit moves the path one step, never a jump back.
        coord = [0] * ndim
        acc = 0
        for i in reversed(range(ndim)):
            c = digits[i] if acc % 2 == 0 else dims[i] - 1 - digits[i]
            coord[i] = c
            acc += c
        yield tuple(coord)


def _topology_ordered(devs: Sequence) -> Optional[List]:
    """Reorder TPU devices so consecutive list entries are ICI-adjacent.

    Uses `device.coords` (the chip's position on the physical torus) and
    `core_on_chip`: cores of one chip are innermost (zero-hop), then chips
    follow a snake path over the torus (single-hop steps). Returns None if
    coords are unavailable (CPU/GPU), duplicated, or the device set is not
    a full box — then the caller keeps jax's own ordering rather than
    guessing adjacency it cannot verify.

    Fixes the VERDICT round-1 finding that `np.reshape` row-major over
    `jax.devices()` puts the latency-bound tp axis on non-adjacent chips of
    a 3D torus (the reference has no analog: torch process groups have no
    topology model at all, reference python/ray/train/torch/config.py:113).
    """
    recs = []
    for d in devs:
        coords = getattr(d, "coords", None)
        if coords is None:
            return None
        try:
            c = tuple(int(x) for x in coords)
        except (TypeError, ValueError):
            return None
        recs.append((c, int(getattr(d, "core_on_chip", 0) or 0), d))
    if not recs:
        return None
    ndim = len(recs[0][0])
    if any(len(c) != ndim for c, _, _ in recs):
        return None
    dims = tuple(max(c[i] for c, _, _ in recs) + 1 for i in range(ndim))
    ncores = max(core for _, core, _ in recs) + 1
    grid = {}
    for c, core, d in recs:
        if (c, core) in grid:
            return None
        grid[(c, core)] = d
    expected = ncores
    for s in dims:
        expected *= s
    if len(grid) != expected:
        return None
    out = []
    for idx in _snake_iter(dims):
        for core in range(ncores):
            out.append(grid[(idx, core)])
    return out


def _group_by_slice(devs: Sequence, num_slices: int) -> List[List]:
    """Partition devices into per-slice groups for a dcn_dp mesh.

    Real multislice TPU devices carry ``slice_index``; group by it. Virtual
    or single-slice device sets (no/constant slice_index) are split evenly —
    the dry-run/CPU stand-in for N slices.
    """
    by_idx: Dict[int, List] = {}
    for d in devs:
        idx = getattr(d, "slice_index", None)
        if idx is None:
            by_idx = {}
            break
        by_idx.setdefault(int(idx), []).append(d)
    if by_idx:
        # REAL slice membership: it must be consistent with the request —
        # silently regrouping would lay ICI axes (tp/pp) across DCN.
        groups = [by_idx[k] for k in sorted(by_idx)][:num_slices]
        if len(by_idx) < num_slices or len({len(g) for g in groups}) != 1:
            raise ValueError(
                f"dcn_dp={num_slices} needs {num_slices} equal slices; "
                f"devices report slice sizes "
                f"{ {k: len(v) for k, v in sorted(by_idx.items())} }")
        return groups
    per = len(devs) // num_slices
    return [list(devs[i * per:(i + 1) * per]) for i in range(num_slices)]


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None, *,
               topology_aware: bool = True):
    """Build a jax Mesh with the spec's axes over `devices`.

    With `topology_aware` (default), devices are first reordered along a
    snake path over their physical torus coordinates so that the innermost
    logical axis (tp — per-layer, latency-bound collectives) maps to
    ICI-adjacent chips and each outer axis to a physically contiguous
    block. Off-TPU (no coords) the jax device order is kept as-is.

    dcn_dp > 1: devices are grouped per slice (``slice_index``), each
    slice's block is topology-ordered independently, and the dcn_dp axis
    strides across slices — so every intra-slice axis stays on ICI and only
    the data axis crosses DCN.
    """
    import jax
    devs = list(devices) if devices is not None else list(jax.devices())
    if spec.num_devices > len(devs):
        raise ValueError(
            f"MeshSpec needs {spec.num_devices} devices, have {len(devs)}")
    if spec.dcn_dp > 1:
        groups = _group_by_slice(devs, spec.dcn_dp)
        per_slice = spec.devices_per_slice
        ordered_groups = []
        for g in groups:
            if len(g) < per_slice:
                raise ValueError(
                    f"dcn_dp={spec.dcn_dp} needs {per_slice} devices per "
                    f"slice, a slice has {len(g)}")
            if topology_aware:
                og = _topology_ordered(g)
                g = og if og is not None else list(g)
            ordered_groups.append(g[:per_slice])
        devs = [d for g in ordered_groups for d in g]
    else:
        if topology_aware:
            ordered = _topology_ordered(devs)
            if ordered is not None:
                devs = ordered
        # Taking a prefix of the snake path keeps a physically contiguous
        # sub-volume when the spec uses fewer devices than the slice has.
        devs = devs[: spec.num_devices]
    shape = [getattr(spec, a) for a in AXIS_ORDER]
    arr = np.array(devs, dtype=object).reshape(shape)
    return jax.sharding.Mesh(arr, AXIS_ORDER)


def local_mesh(**axis_sizes):
    """Convenience: build_mesh(MeshSpec(**axis_sizes)) on all local devices."""
    return build_mesh(MeshSpec(**axis_sizes))


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a per-example batch dimension is sharded over."""
    return ("dcn_dp", "dp", "fsdp")


def best_dp_fsdp_split(num_devices: int, params_bytes: int,
                       hbm_bytes_per_chip: int = 16 << 30) -> MeshSpec:
    """Heuristic: use pure DP until replicated params+opt-state (~4x params
    for adam in f32 master) would not fit; then shard with fsdp."""
    need = params_bytes * 4
    if need <= hbm_bytes_per_chip // 2:
        return MeshSpec(dp=num_devices)
    fsdp = 1
    while fsdp < num_devices and need // fsdp > hbm_bytes_per_chip // 2:
        fsdp *= 2
    return MeshSpec(dp=num_devices // fsdp, fsdp=fsdp)
