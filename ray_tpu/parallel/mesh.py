"""Device-mesh construction over TPU slices.

One `jax.sharding.Mesh` with named axes ("dp","fsdp","tp","pp","sp","ep") is
the substrate of every parallelism strategy. The reference's analog is the
torch process-group bootstrap (reference python/ray/train/torch/config.py:113
dist.init_process_group); here there is no rendezvous per-strategy — you pick
axis sizes once and XLA compiles the collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")
# tp innermost: tensor-parallel collectives are per-layer and latency-bound,
# so tp must map to the fastest (most-adjacent) ICI dimension. pp outermost:
# stage-to-stage transfers happen once per microbatch.


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis sizes for the global device mesh. 1 = strategy off."""
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.pp * self.sp * self.ep

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def active_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if getattr(self, a) > 1)

    @staticmethod
    def auto(num_devices: int, *, tp: int = 1, pp: int = 1, sp: int = 1,
             ep: int = 1, fsdp: Optional[int] = None) -> "MeshSpec":
        """Fill the remaining devices with (fsdp or dp) parallelism."""
        model = tp * pp * sp * ep
        if num_devices % model:
            raise ValueError(
                f"tp*pp*sp*ep={model} does not divide num_devices={num_devices}")
        rest = num_devices // model
        if fsdp is None:
            return MeshSpec(dp=rest, tp=tp, pp=pp, sp=sp, ep=ep)
        if rest % fsdp:
            raise ValueError(f"fsdp={fsdp} does not divide remainder {rest}")
        return MeshSpec(dp=rest // fsdp, fsdp=fsdp, tp=tp, pp=pp, sp=sp, ep=ep)


def mesh_shape_for(spec: MeshSpec) -> Tuple[Tuple[str, int], ...]:
    return tuple((a, getattr(spec, a)) for a in AXIS_ORDER)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a jax Mesh with the spec's axes over `devices`.

    Device order respects ICI adjacency: jax returns devices in topology
    order, and we reshape row-major so the innermost axis (tp) maps to
    adjacent chips.
    """
    import jax
    devs = list(devices) if devices is not None else list(jax.devices())
    if spec.num_devices > len(devs):
        raise ValueError(
            f"MeshSpec needs {spec.num_devices} devices, have {len(devs)}")
    devs = devs[: spec.num_devices]
    shape = [getattr(spec, a) for a in AXIS_ORDER]
    arr = np.array(devs, dtype=object).reshape(shape)
    return jax.sharding.Mesh(arr, AXIS_ORDER)


def local_mesh(**axis_sizes):
    """Convenience: build_mesh(MeshSpec(**axis_sizes)) on all local devices."""
    return build_mesh(MeshSpec(**axis_sizes))


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a per-example batch dimension is sharded over."""
    return ("dp", "fsdp")


def best_dp_fsdp_split(num_devices: int, params_bytes: int,
                       hbm_bytes_per_chip: int = 16 << 30) -> MeshSpec:
    """Heuristic: use pure DP until replicated params+opt-state (~4x params
    for adam in f32 master) would not fit; then shard with fsdp."""
    need = params_bytes * 4
    if need <= hbm_bytes_per_chip // 2:
        return MeshSpec(dp=num_devices)
    fsdp = 1
    while fsdp < num_devices and need // fsdp > hbm_bytes_per_chip // 2:
        fsdp *= 2
    return MeshSpec(dp=num_devices // fsdp, fsdp=fsdp)
