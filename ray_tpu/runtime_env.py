"""RuntimeEnv: per-task/actor environment spec.

Role parity: python/ray/runtime_env/runtime_env.py — a validated dict of
environment customizations applied when the worker pool spawns a process
for that env (node_daemon._spawn_worker): ``env_vars`` merge into the
worker's environment, ``working_dir`` becomes its cwd, ``py_modules`` are
packaged at validation time (zip, content-addressed) and unpacked onto the
worker's PYTHONPATH on the executing node (the role of the reference's
runtime-env agent + GCS package store, _private/runtime_env/py_modules.py).
Workers are cached per runtime-env hash (dedicated-worker behavior).

``pip`` environments (parity: _private/runtime_env/pip.py) build a venv
per spec hash with --system-site-packages and install OFFLINE
(``--no-index``): packages resolve from a ``find_links`` wheel directory
or local paths only — this image has no network egress, so index installs
fail fast with pip's own error. Workers for such envs run on the venv's
interpreter. Conda/container plugins raise upfront.
"""

from __future__ import annotations

import base64
import hashlib
import io
import os
import zipfile
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip"}
_KNOWN_UNSUPPORTED = {"conda", "container"}
_MAX_MODULE_ZIP = 64 << 20


def _pack_module(path: str) -> Dict[str, str]:
    """Zip one module (package dir or single .py) into a portable record.
    Content-addressed so daemons extract each version exactly once."""
    path = os.path.abspath(path)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isfile(path):
            z.write(path, os.path.basename(path))
        elif os.path.isdir(path):
            base = os.path.basename(path.rstrip("/"))
            for root, _dirs, files in os.walk(path):
                for f in files:
                    if f.endswith((".pyc", ".pyo")):
                        continue
                    full = os.path.join(root, f)
                    z.write(full, os.path.join(
                        base, os.path.relpath(full, path)))
        else:
            raise ValueError(f"py_module path {path!r} does not exist")
    raw = buf.getvalue()
    if len(raw) > _MAX_MODULE_ZIP:
        raise ValueError(
            f"py_module {path!r} packs to {len(raw)} bytes "
            f"(limit {_MAX_MODULE_ZIP}); ship big deps in the image")
    return {"name": os.path.basename(path),
            "sha": hashlib.sha256(raw).hexdigest()[:16],
            "zip_b64": base64.b64encode(raw).decode()}


def unpack_py_modules(records: List[dict], dest_root: str) -> str:
    """Daemon-side: extract packed modules under dest_root; returns the
    PYTHONPATH entry to prepend. Idempotent per content hash, and safe
    under concurrent spawns: extraction goes to a private temp dir that is
    atomically renamed into place (a second extractor either loses the
    rename race harmlessly or sees the finished directory)."""
    import tempfile

    paths = []
    for rec in records:
        out_dir = os.path.join(dest_root, rec["sha"])
        if not os.path.isdir(out_dir):
            os.makedirs(dest_root, exist_ok=True)
            tmp = tempfile.mkdtemp(dir=dest_root,
                                   prefix=f".{rec['sha']}-")
            raw = base64.b64decode(rec["zip_b64"])
            with zipfile.ZipFile(io.BytesIO(raw)) as z:
                z.extractall(tmp)
            try:
                os.rename(tmp, out_dir)
            except OSError:
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        paths.append(out_dir)
    return os.pathsep.join(paths)


def env_fingerprint(env: Optional[dict]) -> str:
    """Stable, COMPACT identity for a runtime env: packed module blobs are
    replaced by their content hashes so scheduling keys and worker-cache
    keys never serialize megabytes of zip data."""
    if not env:
        return ""
    import json
    slim = dict(env)
    if slim.get("py_modules"):
        slim["py_modules"] = [
            {"name": r.get("name"), "sha": r.get("sha")}
            for r in slim["py_modules"]]
    return json.dumps(slim, sort_keys=True, default=str)


class RuntimeEnv(dict):
    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[Any] = None, **kwargs):
        super().__init__()
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            self["working_dir"] = working_dir
        if py_modules is not None:
            packed = []
            for m in py_modules:
                if isinstance(m, dict) and "zip_b64" in m:
                    packed.append(dict(m))  # already packed (re-validation)
                else:
                    packed.append(_pack_module(str(m)))
            self["py_modules"] = packed
        if pip is not None:
            # Normalize: ["pkg", ...] or {"packages": [...],
            # "find_links": dir}. Stored small and hashable.
            if isinstance(pip, (list, tuple)):
                spec = {"packages": [str(p) for p in pip],
                        "find_links": None}
            elif isinstance(pip, dict):
                spec = {"packages": [str(p) for p in pip.get("packages", [])],
                        "find_links": pip.get("find_links")}
            else:
                raise TypeError("pip must be a list of requirements or a "
                                "dict with packages/find_links")
            if not spec["packages"]:
                raise ValueError("pip spec has no packages")
            if (spec["find_links"] is not None
                    and not os.path.isdir(spec["find_links"])):
                raise ValueError(
                    f"pip find_links {spec['find_links']!r} is not a "
                    "directory (offline installs need a local wheel dir)")
            self["pip"] = spec
        for k in kwargs:
            if k in _KNOWN_UNSUPPORTED:
                raise ValueError(
                    f"runtime_env field {k!r} requires package installation "
                    "at runtime, which this deployment image disallows; "
                    "bake dependencies into the image instead")
            raise ValueError(f"unknown runtime_env field {k!r}")

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)


def validate_runtime_env(env: Optional[dict]) -> Optional[dict]:
    if env is None:
        return None
    if isinstance(env, RuntimeEnv):
        return env.to_dict()
    return RuntimeEnv(**env).to_dict()


_pip_env_locks: Dict[str, Any] = {}
_pip_env_locks_guard = None


def _pip_lock(key: str):
    global _pip_env_locks_guard
    import threading
    if _pip_env_locks_guard is None:
        _pip_env_locks_guard = threading.Lock()
    with _pip_env_locks_guard:
        return _pip_env_locks.setdefault(key, threading.Lock())


def ensure_pip_env(spec: Dict[str, Any], session_dir: str) -> str:
    """Daemon-side (runtime-env agent role, _private/runtime_env/pip.py):
    materialize the venv for a pip spec and return its python executable.
    Cached per spec hash; --system-site-packages keeps the image's baked
    deps (jax et al.) visible; installs are strictly OFFLINE (--no-index
    [--find-links dir]) because this image has no egress."""
    import hashlib
    import json
    import subprocess
    import sys

    from ray_tpu.core.exceptions import RuntimeEnvSetupError

    key = hashlib.sha256(json.dumps(spec, sort_keys=True).encode()
                         ).hexdigest()[:16]
    root = os.path.join(session_dir, "pip_envs", key)
    py = os.path.join(root, "bin", "python")
    marker = os.path.join(root, ".ready")
    if os.path.exists(marker):
        return py
    # Per-spec build lock: the daemon's RPC server is threaded, and two
    # concurrent leases for the same env must not race `venv` + `pip`
    # into one directory (a corrupted build would read as a DETERMINISTIC
    # env failure and fail-fast every queued task).
    with _pip_lock(key):
        if os.path.exists(marker):
            return py
        try:
            _build_pip_env(spec, root, py)
        except RuntimeEnvSetupError:
            raise
        except Exception as e:  # venv/ensurepip/site-probe failures
            raise RuntimeEnvSetupError(
                f"pip runtime_env venv build failed: {e!r}") from e
        with open(marker, "w") as f:
            f.write("ok")
    return py


def _build_pip_env(spec: Dict[str, Any], root: str, py: str) -> None:
    import json
    import subprocess
    import sys

    import shutil
    shutil.rmtree(root, ignore_errors=True)   # clear any partial build
    subprocess.run([sys.executable, "-m", "venv",
                    "--system-site-packages", root],
                   check=True, capture_output=True)
    # When the PARENT interpreter is itself a venv (this image: /opt/venv),
    # --system-site-packages points at the base python, not the parent —
    # so the image's baked deps (jax, cloudpickle, ...) would vanish.
    # A .pth in the child exposes the parent's site-packages explicitly.
    import site
    child_site = subprocess.run(
        [py, "-c", "import site, json;"
         "print(json.dumps(site.getsitepackages()))"],
        check=True, capture_output=True, text=True)
    child_dirs = json.loads(child_site.stdout)
    parent_dirs = [d for d in site.getsitepackages()
                   if d not in child_dirs and os.path.isdir(d)]
    if child_dirs and parent_dirs:
        with open(os.path.join(child_dirs[0], "_parent_site.pth"),
                  "w") as f:
            f.write("\n".join(parent_dirs) + "\n")
    cmd = [py, "-m", "pip", "install", "--no-index",
           "--disable-pip-version-check"]
    if spec.get("find_links"):
        cmd += ["--find-links", spec["find_links"]]
    cmd += spec["packages"]
    out = subprocess.run(cmd, capture_output=True, text=True)
    if out.returncode != 0:
        from ray_tpu.core.exceptions import RuntimeEnvSetupError
        raise RuntimeEnvSetupError(
            f"pip runtime_env install failed (offline --no-index; provide "
            f"find_links with local wheels): {out.stderr.strip()[-500:]}")
