"""RuntimeEnv: per-task/actor environment spec.

Role parity: python/ray/runtime_env/runtime_env.py — a validated dict of
environment customizations applied when the worker pool spawns a process
for that env (node_daemon._spawn_worker): ``env_vars`` merge into the
worker's environment, ``working_dir`` becomes its cwd. Workers are cached
per runtime-env hash (the reference's dedicated-worker behavior).

Unsupported-in-this-image plugins (pip/conda/container) raise upfront
rather than failing inside the worker pool.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "working_dir"}
_KNOWN_UNSUPPORTED = {"pip", "conda", "container", "py_modules"}


class RuntimeEnv(dict):
    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None, **kwargs):
        super().__init__()
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            import os
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            self["working_dir"] = working_dir
        for k in kwargs:
            if k in _KNOWN_UNSUPPORTED:
                raise ValueError(
                    f"runtime_env field {k!r} requires package installation "
                    "at runtime, which this deployment image disallows; "
                    "bake dependencies into the image instead")
            raise ValueError(f"unknown runtime_env field {k!r}")

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)


def validate_runtime_env(env: Optional[dict]) -> Optional[dict]:
    if env is None:
        return None
    if isinstance(env, RuntimeEnv):
        return env.to_dict()
    return RuntimeEnv(**env).to_dict()
