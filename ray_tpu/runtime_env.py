"""RuntimeEnv: per-task/actor environment spec.

Role parity: python/ray/runtime_env/runtime_env.py — a validated dict of
environment customizations applied when the worker pool spawns a process
for that env (node_daemon._spawn_worker): ``env_vars`` merge into the
worker's environment, ``working_dir`` becomes its cwd, ``py_modules`` are
packaged at validation time (zip, content-addressed) and unpacked onto the
worker's PYTHONPATH on the executing node (the role of the reference's
runtime-env agent + GCS package store, _private/runtime_env/py_modules.py).
Workers are cached per runtime-env hash (dedicated-worker behavior).

Unsupported-in-this-image plugins (pip/conda/container) raise upfront
rather than failing inside the worker pool.
"""

from __future__ import annotations

import base64
import hashlib
import io
import os
import zipfile
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules"}
_KNOWN_UNSUPPORTED = {"pip", "conda", "container"}
_MAX_MODULE_ZIP = 64 << 20


def _pack_module(path: str) -> Dict[str, str]:
    """Zip one module (package dir or single .py) into a portable record.
    Content-addressed so daemons extract each version exactly once."""
    path = os.path.abspath(path)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isfile(path):
            z.write(path, os.path.basename(path))
        elif os.path.isdir(path):
            base = os.path.basename(path.rstrip("/"))
            for root, _dirs, files in os.walk(path):
                for f in files:
                    if f.endswith((".pyc", ".pyo")):
                        continue
                    full = os.path.join(root, f)
                    z.write(full, os.path.join(
                        base, os.path.relpath(full, path)))
        else:
            raise ValueError(f"py_module path {path!r} does not exist")
    raw = buf.getvalue()
    if len(raw) > _MAX_MODULE_ZIP:
        raise ValueError(
            f"py_module {path!r} packs to {len(raw)} bytes "
            f"(limit {_MAX_MODULE_ZIP}); ship big deps in the image")
    return {"name": os.path.basename(path),
            "sha": hashlib.sha256(raw).hexdigest()[:16],
            "zip_b64": base64.b64encode(raw).decode()}


def unpack_py_modules(records: List[dict], dest_root: str) -> str:
    """Daemon-side: extract packed modules under dest_root; returns the
    PYTHONPATH entry to prepend. Idempotent per content hash, and safe
    under concurrent spawns: extraction goes to a private temp dir that is
    atomically renamed into place (a second extractor either loses the
    rename race harmlessly or sees the finished directory)."""
    import tempfile

    paths = []
    for rec in records:
        out_dir = os.path.join(dest_root, rec["sha"])
        if not os.path.isdir(out_dir):
            os.makedirs(dest_root, exist_ok=True)
            tmp = tempfile.mkdtemp(dir=dest_root,
                                   prefix=f".{rec['sha']}-")
            raw = base64.b64decode(rec["zip_b64"])
            with zipfile.ZipFile(io.BytesIO(raw)) as z:
                z.extractall(tmp)
            try:
                os.rename(tmp, out_dir)
            except OSError:
                import shutil
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        paths.append(out_dir)
    return os.pathsep.join(paths)


def env_fingerprint(env: Optional[dict]) -> str:
    """Stable, COMPACT identity for a runtime env: packed module blobs are
    replaced by their content hashes so scheduling keys and worker-cache
    keys never serialize megabytes of zip data."""
    if not env:
        return ""
    import json
    slim = dict(env)
    if slim.get("py_modules"):
        slim["py_modules"] = [
            {"name": r.get("name"), "sha": r.get("sha")}
            for r in slim["py_modules"]]
    return json.dumps(slim, sort_keys=True, default=str)


class RuntimeEnv(dict):
    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None, **kwargs):
        super().__init__()
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            self["working_dir"] = working_dir
        if py_modules is not None:
            packed = []
            for m in py_modules:
                if isinstance(m, dict) and "zip_b64" in m:
                    packed.append(dict(m))  # already packed (re-validation)
                else:
                    packed.append(_pack_module(str(m)))
            self["py_modules"] = packed
        for k in kwargs:
            if k in _KNOWN_UNSUPPORTED:
                raise ValueError(
                    f"runtime_env field {k!r} requires package installation "
                    "at runtime, which this deployment image disallows; "
                    "bake dependencies into the image instead")
            raise ValueError(f"unknown runtime_env field {k!r}")

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)


def validate_runtime_env(env: Optional[dict]) -> Optional[dict]:
    if env is None:
        return None
    if isinstance(env, RuntimeEnv):
        return env.to_dict()
    return RuntimeEnv(**env).to_dict()
