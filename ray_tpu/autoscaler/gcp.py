"""GCP TPU-VM node provider.

Role parity: python/ray/autoscaler/_private/gcp/node_provider.py +
gcp/tpu.py — the reference launches GCE instances / TPU VMs via the
googleapiclient. Here the provider drives the TPU VM API through an
injectable transport (`GcpTpuApi`): production uses the `gcloud` CLI (the
only GCP surface guaranteed present on TPU pods; zero extra deps), tests
inject a fake. TPU-first specifics the reference's GCE path lacks:

- a node type IS an accelerator topology (`accelerator_type:
  "v5litepod-8"`), so scale-up units are whole ICI slices, never single
  VMs — matching the SLICE scheduling strategy's placement unit;
- the startup script joins every host of the created slice to the
  conductor (`ray_tpu start --address=...`), and the daemon's slice
  detection (tpu/topology.py) advertises slice membership from metadata.
"""

from __future__ import annotations

import json
import shlex
import subprocess
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.autoscaler import NodeProvider


class GcpTpuApi:
    """Transport to the TPU VM control plane. Production: gcloud CLI."""

    def __init__(self, project: str, zone: str):
        self.project = project
        self.zone = zone

    def _run(self, *args: str) -> str:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}",
               "--format=json"]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=300)
        if out.returncode != 0:
            raise RuntimeError(
                f"gcloud failed ({' '.join(map(shlex.quote, cmd))}): "
                f"{out.stderr.strip()}")
        return out.stdout

    def create(self, name: str, accelerator_type: str, version: str,
               startup_script: str, labels: Dict[str, str]) -> None:
        label_arg = ",".join(f"{k}={v}" for k, v in labels.items())
        self._run("create", name,
                  f"--accelerator-type={accelerator_type}",
                  f"--version={version}", f"--labels={label_arg}",
                  f"--metadata=startup-script={startup_script}")

    def delete(self, name: str) -> None:
        self._run("delete", name, "--quiet")

    def list(self, label_filter: Dict[str, str]) -> List[dict]:
        flt = " AND ".join(f"labels.{k}={v}"
                           for k, v in label_filter.items())
        out = self._run("list", f"--filter={flt}")
        return json.loads(out or "[]")


class GcpTpuNodeProvider(NodeProvider):
    """Slice-granular TPU-VM provider.

    node_types: {type_name: {"accelerator_type": "v5litepod-8",
                             "version": "tpu-ubuntu2204-base",
                             "resources": {...}, "max_workers": N}}
    """

    CLUSTER_LABEL = "ray-tpu-cluster"
    TYPE_LABEL = "ray-tpu-node-type"

    def __init__(self, conductor_address: str, node_types: Dict[str, dict],
                 *, cluster_name: str = "default", api: GcpTpuApi = None,
                 project: str = "", zone: str = ""):
        self.conductor_address = conductor_address
        self.node_types = node_types
        self.cluster_name = cluster_name
        self.api = api if api is not None else GcpTpuApi(project, zone)
        self._lock = threading.Lock()
        self._created: Dict[str, str] = {}   # name -> type

    def _startup_script(self, node_type: str) -> str:
        # Every host of the slice joins as a daemon; slice metadata is
        # detected on-host (tpu/topology.py reads the TPU env).
        return ("#!/bin/bash\n"
                "python -m ray_tpu.scripts start "
                f"--address={self.conductor_address} --block\n")

    def create_node(self, node_type: str) -> str:
        cfg = self.node_types[node_type]
        name = f"ray-tpu-{self.cluster_name}-{node_type}-" \
               f"{uuid.uuid4().hex[:8]}"
        self.api.create(
            name, cfg["accelerator_type"],
            cfg.get("version", "tpu-ubuntu2204-base"),
            self._startup_script(node_type),
            labels={self.CLUSTER_LABEL: self.cluster_name,
                    self.TYPE_LABEL: node_type})
        with self._lock:
            self._created[name] = node_type
        return name

    def terminate_node(self, provider_id: str) -> None:
        try:
            self.api.delete(provider_id)
        except RuntimeError:
            # Idempotent: already deleted (e.g. a prior pass won the race)
            # must not crash the autoscaler's reconcile loop.
            pass
        with self._lock:
            self._created.pop(provider_id, None)

    # VM states that serve no capacity and should neither count against
    # max_workers nor block replacement launches.
    _DEAD_STATES = ("DELETING", "TERMINATED", "PREEMPTED", "STOPPED",
                    "STOPPING", "SUSPENDED")

    def non_terminated_nodes(self) -> List[Tuple[str, str]]:
        nodes = self.api.list({self.CLUSTER_LABEL: self.cluster_name})
        out: List[Tuple[str, str]] = []
        for n in nodes:
            if n.get("state") in self._DEAD_STATES:
                continue
            name = n["name"].rsplit("/", 1)[-1]
            ntype = (n.get("labels") or {}).get(self.TYPE_LABEL, "")
            out.append((name, ntype))
        return out

    def node_id_map(self) -> Dict[bytes, str]:
        """cluster node_id -> TPU-VM name, joined on the daemon-advertised
        slice id (tpu/topology.py detect_slice reads TPU_NAME, which is the
        TPU-VM resource name on Cloud TPU pods)."""
        from ray_tpu.cluster.protocol import get_client
        try:
            nodes = get_client(self.conductor_address).call("get_nodes")
        except Exception:
            return {}
        # Membership comes from the label-filtered CLOUD listing (survives
        # provider restarts), not process-local create history.
        known = {name for name, _ in self.non_terminated_nodes()}
        mapping: Dict[bytes, str] = {}
        for n in nodes:
            slice_info = n.get("tpu_slice") or {}
            # Join on the TPU-VM resource name (tpu_name). slice_id is the
            # MEGASCALE slice index on multislice — never a VM name.
            name = slice_info.get("tpu_name") or slice_info.get("slice_id")
            if name in known:
                mapping[n["node_id"]] = name
        return mapping
