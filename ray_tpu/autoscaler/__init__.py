"""ray_tpu.autoscaler — load-driven cluster scaling.

Parity surface: reference python/ray/autoscaler — StandardAutoscaler
(_private/autoscaler.py:172), bin-packing ResourceDemandScheduler
(_private/resource_demand_scheduler.py:101), pluggable NodeProvider
(node_provider.py) with the fake in-process provider
(_private/fake_multi_node/) for tests.

TPU-first: a node type carries a ``topology`` (e.g. "v4-8") — scaling up a
TPU type means provisioning a whole ICI slice's hosts at once (slice
granularity, not per-VM), which is how TPU capacity actually arrives.
"""

from ray_tpu.autoscaler.autoscaler import (FakeNodeProvider, NodeProvider,
                                           StandardAutoscaler,
                                           fit_demand)

__all__ = ["StandardAutoscaler", "NodeProvider", "FakeNodeProvider",
           "fit_demand"]
