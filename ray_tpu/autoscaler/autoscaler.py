"""Autoscaler core: demand bin-packing + provider reconciliation."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class NodeProvider:
    """Cloud abstraction (parity: autoscaler/node_provider.py)."""

    def create_node(self, node_type: str) -> str:
        """Launch one node of ``node_type``; returns provider node id."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Tuple[str, str]]:
        """-> [(provider_id, node_type)]."""
        raise NotImplementedError

    def node_id_map(self) -> Dict[bytes, str]:
        """cluster node_id -> provider_id, for scale-down. Providers that
        cannot map (yet) return {} and opt out of termination."""
        return {}

    def can_map(self, provider_id: str) -> bool:
        """Whether node_id_map COULD ever map a cluster node to this
        provider node. The zombie sweep must skip nodes the provider is
        structurally blind to (e.g. a head/CPU VM in a TPU-only mapping) —
        'unmapped' only means 'dead or never joined' for mappable ones."""
        return True


class FakeNodeProvider(NodeProvider):
    """In-process provider: "launching a node" starts a NodeDaemon thread
    against the conductor (parity: _private/fake_multi_node)."""

    def __init__(self, conductor_address: str,
                 node_types: Dict[str, Dict[str, float]]):
        self.conductor_address = conductor_address
        self.node_types = node_types
        self._nodes: Dict[str, tuple] = {}   # provider_id -> (daemon, type)
        self._counter = 0
        self._lock = threading.Lock()

    def create_node(self, node_type: str) -> str:
        from ray_tpu.cluster.node_daemon import NodeDaemon
        resources = dict(self.node_types[node_type]["resources"])
        daemon = NodeDaemon(self.conductor_address, resources=resources,
                            object_store_bytes=64 << 20)
        with self._lock:
            self._counter += 1
            pid = f"fake-{node_type}-{self._counter}"
            self._nodes[pid] = (daemon, node_type)
        return pid

    def terminate_node(self, provider_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(provider_id, None)
        if entry:
            entry[0].stop()

    def non_terminated_nodes(self) -> List[Tuple[str, str]]:
        with self._lock:
            return [(pid, t) for pid, (d, t) in self._nodes.items()]

    def daemon_node_id(self, provider_id: str) -> Optional[bytes]:
        entry = self._nodes.get(provider_id)
        return entry[0].node_id if entry else None

    def node_id_map(self) -> Dict[bytes, str]:
        with self._lock:
            return {d.node_id: pid for pid, (d, t) in self._nodes.items()}


def _fits(avail: Dict[str, float], shape: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in shape.items()
               if v > 0)


def _take(avail: Dict[str, float], shape: Dict[str, float]) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


def fit_demand(demand: List[Dict[str, float]],
               node_avail: List[Dict[str, float]],
               node_types: Dict[str, dict],
               max_per_type: Optional[Dict[str, int]] = None
               ) -> Dict[str, int]:
    """Bin-pack pending demand onto existing capacity; whatever is left
    maps to new nodes by type (parity: resource_demand_scheduler.py:101
    get_nodes_to_launch)."""
    avail = [dict(a) for a in node_avail]
    unmet: List[Dict[str, float]] = []
    for shape in demand:
        placed = False
        for a in avail:
            if _fits(a, shape):
                _take(a, shape)
                placed = True
                break
        if not placed:
            unmet.append(shape)
    to_launch: Dict[str, int] = {}
    virtual: List[Dict[str, float]] = []
    for shape in unmet:
        placed = False
        for v in virtual:
            if _fits(v, shape):
                _take(v, shape)
                placed = True
                break
        if placed:
            continue
        for tname, tcfg in node_types.items():
            res = tcfg["resources"]
            cap = (max_per_type or {}).get(
                tname, tcfg.get("max_workers", 10))
            if to_launch.get(tname, 0) >= cap:
                continue
            if _fits(dict(res), shape):
                to_launch[tname] = to_launch.get(tname, 0) + 1
                fresh = dict(res)
                _take(fresh, shape)
                virtual.append(fresh)
                placed = True
                break
        # unplaceable on any type -> dropped (infeasible demand)
    return to_launch


class StandardAutoscaler:
    """Reconcile loop (parity: autoscaler.py:172 StandardAutoscaler.update):
    read load from the conductor, launch nodes for unmet demand, terminate
    nodes idle past the timeout."""

    def __init__(self, conductor_address: str, provider: NodeProvider,
                 node_types: Dict[str, dict],
                 idle_timeout_s: float = 30.0,
                 update_interval_s: float = 1.0,
                 max_workers: int = 20,
                 zombie_grace_s: float = 600.0,
                 min_per_type: Optional[Dict[str, int]] = None):
        from ray_tpu.cluster.protocol import get_client
        self.conductor = get_client(conductor_address)
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self.max_workers = max_workers
        # Reconciled per-type floor (cluster-launcher min_workers): the
        # loop replenishes below-floor types and idle-termination never
        # drops a type below it.
        self.min_per_type = dict(min_per_type or {})
        # How long a provider node may run with ZERO registered cluster
        # nodes before it is terminated (covers boot time; after that it's
        # a cost leak — dead slice or broken startup script). The default
        # must exceed worst-case multi-host slice provisioning+boot, or
        # scale-up churns: launch → terminate-at-grace → relaunch.
        self.zombie_grace_s = zombie_grace_s
        self._idle_since: Dict[bytes, float] = {}
        self._zombie_since: Dict[str, float] = {}
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    def update(self) -> Dict[str, int]:
        """One reconcile pass; returns what was launched."""
        load = self.conductor.call("cluster_load")
        workers = self.provider.non_terminated_nodes()
        launched: Dict[str, int] = {}
        # Replenish the per-type floor first (a zombie sweep or crash may
        # have dropped below it).
        if self.min_per_type:
            have: Dict[str, int] = {}
            for _, t in workers:
                have[t] = have.get(t, 0) + 1
            for tname, floor in self.min_per_type.items():
                for _ in range(max(0, floor - have.get(tname, 0))):
                    if len(workers) + sum(launched.values()) >= \
                            self.max_workers:
                        break
                    self.provider.create_node(tname)
                    launched[tname] = launched.get(tname, 0) + 1
        if len(workers) + sum(launched.values()) < self.max_workers:
            # per-type caps are cluster-wide: subtract what already runs
            # AND what the replenish loop above just launched (those nodes
            # aren't in non_terminated_nodes() yet; ignoring them lets one
            # reconcile pass overshoot max_workers / per-type caps).
            existing: Dict[str, int] = dict(launched)
            for _, t in workers:
                existing[t] = existing.get(t, 0) + 1
            caps = {t: max(0, cfg.get("max_workers", 10) -
                           existing.get(t, 0))
                    for t, cfg in self.node_types.items()}
            to_launch = fit_demand(
                load["demand"],
                [n["resources_available"] for n in load["nodes"]],
                self.node_types, max_per_type=caps)
            for tname, count in to_launch.items():
                for _ in range(count):
                    if len(workers) + sum(launched.values()) >= \
                            self.max_workers:
                        break
                    self.provider.create_node(tname)
                    launched[tname] = launched.get(tname, 0) + 1
        # scale down: terminate provider nodes idle past the timeout.
        # Termination is per PROVIDER node: a multi-host TPU slice maps
        # several cluster nodes to one provider id, and the slice may only
        # be deleted when EVERY one of its hosts has been idle past the
        # timeout (deleting on one idle host would kill work on the rest).
        now = time.monotonic()
        by_node_id = self.provider.node_id_map()
        per_provider: Dict[str, List[bytes]] = {}
        for n in load["nodes"]:
            nid = n["node_id"]
            if n["is_head"] or nid not in by_node_id:
                continue
            per_provider.setdefault(by_node_id[nid], []).append(nid)
            idle = n["resources_available"] == n["resources_total"] and \
                not load["demand"]
            if idle:
                self._idle_since.setdefault(nid, now)
            else:
                self._idle_since.pop(nid, None)
        type_of = dict(workers)
        remaining: Dict[str, int] = {}
        for _, t in workers:
            remaining[t] = remaining.get(t, 0) + 1
        for provider_id, nids in per_provider.items():
            if all(nid in self._idle_since and
                   now - self._idle_since[nid] > self.idle_timeout_s
                   for nid in nids):
                t = type_of.get(provider_id, "")
                if remaining.get(t, 0) <= self.min_per_type.get(t, 0):
                    continue  # never drop below the floor
                remaining[t] = remaining.get(t, 0) - 1
                self.provider.terminate_node(provider_id)
                for nid in nids:
                    self._idle_since.pop(nid, None)
        # Prune idle tracking for nodes that vanished from the cluster
        # view (died / deregistered) so stale entries don't accumulate.
        live_nids = {n["node_id"] for n in load["nodes"]}
        for nid in list(self._idle_since):
            if nid not in live_nids:
                self._idle_since.pop(nid, None)
        # Zombie providers: a non-terminated provider node with NO
        # registered cluster node (every host of the slice died, or the
        # startup script never joined). Scale-down above only examines
        # providers with live cluster nodes, so without this sweep such a
        # VM would never be terminated — a pure cost leak. "Registered" is
        # judged from the provider's own node_id_map over ALL live nodes
        # (head included — per_provider excludes it); a provider whose map
        # is empty cannot distinguish booting from dead and opts out of
        # termination entirely (NodeProvider.node_id_map contract).
        if by_node_id:
            registered = {by_node_id[nid] for nid in live_nids
                          if nid in by_node_id}
            for pid, _t in workers:
                if pid in registered:
                    self._zombie_since.pop(pid, None)
                elif not self.provider.can_map(pid):
                    # The provider can never map this node (e.g. a head VM
                    # in a TPU-slice-only mapping): unmapped is NOT a death
                    # signal for it — terminating would kill a live VM.
                    self._zombie_since.pop(pid, None)
                elif now - self._zombie_since.setdefault(pid, now) > \
                        self.zombie_grace_s:
                    self.provider.terminate_node(pid)
                    self._zombie_since.pop(pid, None)
        alive_pids = {pid for pid, _t in workers}
        for pid in list(self._zombie_since):
            if pid not in alive_pids:
                self._zombie_since.pop(pid, None)
        return launched

    def start(self) -> None:
        def loop():
            while not self._stopped:
                try:
                    self.update()
                except Exception:
                    pass
                time.sleep(self.update_interval_s)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stopped = True
