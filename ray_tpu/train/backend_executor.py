"""BackendExecutor: orchestrates the training gang.

Role parity: python/ray/train/_internal/backend_executor.py:43 — start the
WorkerGroup, run the backend's on_start (rendezvous), start the user loop on
every worker, then pump reports until all ranks finish.

The JaxBackend replaces the reference's _TorchBackend
(train/torch/config.py:155): instead of dist.init_process_group(nccl), it
seeds ``jax.distributed.initialize`` with a coordinator on rank 0
(coordination-service rendezvous; collectives then compile into the step
function and ride ICI) — SURVEY.md §3.4 "TPU mapping".
"""

from __future__ import annotations

import socket
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.worker_group import WorkerGroup


class Backend:
    def on_start(self, worker_group: WorkerGroup) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        pass


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int) -> bool:
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


class JaxBackend(Backend):
    """Multi-process SPMD rendezvous (parity role: _TorchBackend)."""

    def __init__(self, distributed: bool = True):
        self.distributed = distributed

    def on_start(self, worker_group: WorkerGroup) -> None:
        if not self.distributed or worker_group.num_workers == 1:
            return
        # Rank 0's host picks the coordinator port; every rank calls
        # jax.distributed.initialize against it (replaces NCCL unique-id
        # rendezvous through the GCS KV, reference nccl_util.py).
        ip = worker_group.execute_single(
            0, lambda: socket.gethostbyname(socket.gethostname()))
        port = worker_group.execute_single(0, _free_port)
        coordinator = f"{ip}:{port}"
        import ray_tpu as rt
        refs = [
            w.execute.remote(_init_jax_distributed, coordinator,
                             worker_group.num_workers, rank)
            for rank, w in enumerate(worker_group.workers)
        ]
        rt.get(refs, timeout=120)


def _init_torch_distributed(master_addr: str, master_port: int,
                            world_size: int, rank: int,
                            backend: str = "gloo") -> bool:
    import os

    import torch.distributed as dist
    if dist.is_initialized():
        return True
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    # gloo default: the CPU-host collective backend (the reference's
    # non-GPU path, train/torch/config.py backend="gloo"); TPU-side math
    # never goes through torch — this exists for torch data/eval loops.
    dist.init_process_group(backend, rank=rank, world_size=world_size)
    return True


class TorchBackend(Backend):
    """torch.distributed rendezvous over the gang (parity:
    train/torch/config.py:113 _TorchBackend.on_start). The group is
    initialized even at world size 1 so loops using torch.distributed
    APIs behave identically in debug (1-worker) runs."""

    def __init__(self, backend: str = "gloo"):
        self.backend_name = backend

    def on_start(self, worker_group: WorkerGroup) -> None:
        ip = worker_group.execute_single(
            0, lambda: socket.gethostbyname(socket.gethostname()))
        port = worker_group.execute_single(0, _free_port)
        import ray_tpu as rt
        refs = [
            w.execute.remote(_init_torch_distributed, ip, port,
                             worker_group.num_workers, rank,
                             self.backend_name)
            for rank, w in enumerate(worker_group.workers)
        ]
        rt.get(refs, timeout=120)

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        def _destroy():
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
            return True
        try:
            worker_group.execute(_destroy)
        except Exception:
            pass  # workers may already be gone


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend: Backend, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 slice_topology: str = ""):
        self.backend = backend
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.slice_topology = slice_topology
        self.worker_group: Optional[WorkerGroup] = None

    def start(self, ready_timeout: float = 120.0) -> None:
        try:
            self.worker_group = WorkerGroup(
                self.num_workers, self.resources_per_worker,
                self.placement_strategy, slice_topology=self.slice_topology,
                ready_timeout=ready_timeout)
            self.backend.on_start(self.worker_group)
        except Exception as e:  # noqa: BLE001 - retryable via FailureConfig
            raise TrainingFailedError(f"gang formation failed: {e!r}") from e

    def run(self, train_loop: Callable, config: dict,
            on_report: Callable[[dict], Any],
            trial_dir: str = "",
            checkpoint: Optional[Checkpoint] = None,
            datasets: Optional[Dict[str, Any]] = None) -> List[dict]:
        """Start the loop on all ranks and pump synchronized reports.

        ``on_report`` receives the merged report each round (rank-0 metrics
        + rank-0 checkpoint); returning "stop" requests cooperative stop.
        Returns the full merged report history.
        """
        import ray_tpu as rt
        wg = self.worker_group
        # Per-rank dataset shards (session.get_dataset_shard): each named
        # Dataset splits into world_size EQUAL-row pieces — collective-per-
        # step loops need the same step count on every rank or the gang
        # deadlocks on the uneven tail (Dataset.split(equal=True) parity).
        shards_by_rank: List[Optional[dict]] = [None] * len(wg.workers)
        if datasets:
            per_name = {name: ds.split(len(wg.workers), equal=True)
                        for name, ds in datasets.items()}
            shards_by_rank = [
                {name: splits[rank] for name, splits in per_name.items()}
                for rank in range(len(wg.workers))]
        try:
            rt.get([w.start_training.remote(train_loop, config, trial_dir,
                                            checkpoint,
                                            dataset_shards=shards_by_rank[i])
                    for i, w in enumerate(wg.workers)], timeout=600)
        except Exception as e:  # noqa: BLE001 - gang infra failure
            raise TrainingFailedError(f"gang start failed: {e!r}") from e
        history: List[dict] = []
        index = 0
        finished = False
        while not finished:
            # One synchronized round: wait for report[index] on every rank
            # (session.report is a barrier in the reference's semantics).
            round_reports: List[Optional[dict]] = [None] * len(wg.workers)
            pending = set(range(len(wg.workers)))
            while pending:
                # Poll the whole round concurrently under ONE shared
                # deadline: submit every rank's long-poll up front, then
                # collect. Serial per-rank polling with a fresh 120s get
                # each meant one hung rank delayed dead-rank detection on
                # every rank queued behind it by up to 120s apiece. A rank
                # still training answers "pending" within its 30s
                # long-poll, re-arming the next wave's deadline — only a
                # rank that cannot answer at all eats the full window.
                wave = {rank: wg.workers[rank].next_report.remote(index, 30.0)
                        for rank in sorted(pending)}
                wave_deadline = time.monotonic() + 120.0
                for rank, ref in wave.items():
                    try:
                        r = rt.get(ref, timeout=max(
                            5.0, wave_deadline - time.monotonic()))
                    except TrainingFailedError:
                        raise
                    except Exception as e:  # noqa: BLE001 - rank died
                        # A dead rank (node loss, OOM kill) fails the whole
                        # gang: an SPMD program cannot continue minus one
                        # process — the trainer re-forms the gang (possibly
                        # smaller, FailureConfig.elastic) from the last
                        # checkpoint.
                        raise TrainingFailedError(
                            f"rank {rank} failed: {e!r}") from e
                    if r["status"] == "report":
                        round_reports[rank] = r
                        pending.discard(rank)
                    elif r["status"] == "finished":
                        round_reports[rank] = None
                        pending.discard(rank)
                        finished = True
                    elif r["status"] == "error":
                        raise TrainingFailedError(r["traceback"])
                    # "pending": poll again
            if all(r is None for r in round_reports):
                break
            rank0 = round_reports[0]
            if rank0 is not None:
                merged = {"metrics": rank0["metrics"],
                          "checkpoint": rank0["checkpoint"],
                          "iteration": rank0["iteration"]}
                history.append(merged)
                if on_report(merged) == "stop":
                    for w in wg.workers:
                        w.request_stop.remote()
                    finished = True
            index += 1
        return history

    def shutdown(self) -> None:
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
