"""MPMD pipeline-parallel training compiled onto cgraph channels.

Role parity: the MPMD 1F1B executor of "Scaling Deep Learning Training
with MPMD Pipeline Parallelism" (PAPERS.md), built from the pieces this
repo already has — transformer layer partitions (models/transformer.py
``_stage_apply`` slices), static per-actor schedules (dag/schedule.py),
and the r11 compiled-graph transport (dag/channel.py rings same-host,
pipelined-RPC forwarder cross-host, object-store spill for oversized
tensors). Contrast with ops/pipeline.py, which is the SPMD shard_map/
ppermute pipeline inside one program: here every stage is its own actor
process running a resident ``ScheduledWorkerLoop``, so steady state
costs channel slot writes — never task RPCs — and stage compute
overlaps neighbor transfer.

Three layers:

- ``PipelineStageActor`` — hosts one or more layer partitions; jit's
  forward / recompute-backward / loss per partition, accumulates grads
  across microbatches, applies the optimizer in-loop (``pipe_apply``)
  or on driver command (``pipe_report`` + ``apply_external`` when DP
  replicas average grads first).
- ``CompiledPipeline`` — model-agnostic driver: mints the channel
  topology (input/targets feeds, activation + gradient edges, per-actor
  done rings), compiles the schedule into per-actor op programs, installs
  the loops, and paces training steps through the rings with poison-
  aware collection and bubble-bound efficiency accounting.
- ``PipelineTrainer`` — the user-facing trainer beside trainer.py:
  partitions a TransformerConfig model, optionally replicates the whole
  pipeline ``dp_replicas`` times (grad averaging between steps), and
  exposes ``step()`` / ``train()``.

Failure semantics match compiled graphs: a stage exception (or injected
``cgraph.loop.crash``) poisons every out channel at its next-unwritten
slot, downstream loops forward and unwind, and the driver's collect
raises the original error fast instead of waiting out the step deadline.
``teardown()`` uninstalls loops, deletes every ring segment (with a
daemon-side backstop for rings owned by a dead worker), and returns the
actors to classic task service.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.dag import schedule as pipesched
from ray_tpu.dag.channel import (FLAG_POISON, RpcChannelWriter,
                                 ShmChannelReader, ShmChannelWriter,
                                 make_channel_id)
from ray_tpu.dag.compiled import (_decode_value, _encode_value, _events,
                                  _live_graphs, _read_slot, _write_slot)


def _runtime():
    from ray_tpu.core.api import _global_runtime
    return _global_runtime()


# ---------------------------------------------------------------------------
# stage actors
# ---------------------------------------------------------------------------

class PipelineStageActor:
    """Hosts the layer partitions assigned to one pipeline stage.

    All jax work is lazy (first touch jits per partition); backward uses
    recompute — the forward stashes only its INPUT per microbatch, and
    the backward replays the partition under ``jax.vjp``, trading FLOPs
    for stash memory exactly like remat inside the layer scan."""

    def __init__(self, cfg, owned_parts: Sequence[int], tx_factory=None):
        self.cfg = cfg
        self.owned = sorted(int(p) for p in owned_parts)
        self._tx_factory = tx_factory
        self._tx = None
        self._params: Dict[int, Any] = {}
        self._opt: Dict[int, Any] = {}
        self._grads: Dict[int, Any] = {}
        self._stash: Dict[tuple, Any] = {}
        self._jit: Dict[int, tuple] = {}
        self._loss_sum = 0.0
        self._loss_n = 0

    # -- setup (classic task service) ------------------------------------

    def ping(self) -> str:
        return "pong"

    def load_partition(self, part: int, params) -> int:
        import jax
        if self._tx is None:
            self._tx = (self._tx_factory or _default_tx_factory)()
        part = int(part)
        self._params[part] = jax.tree.map(lambda a: a, params)
        self._opt[part] = self._tx.init(self._params[part])
        return part

    # -- jit'd per-partition kernels -------------------------------------

    def _fns(self, part: int):
        fns = self._jit.get(part)
        if fns is not None:
            return fns
        import jax
        import jax.numpy as jnp
        from ray_tpu.models.transformer import (transformer_stage_forward,
                                                transformer_stage_loss)
        cfg = self.cfg
        last = cfg.pp_stages - 1

        def fwd(params, x):
            shape = x.shape[:2]
            positions = jnp.broadcast_to(jnp.arange(shape[1]), shape)
            return transformer_stage_forward(params, x, positions, cfg,
                                             part=part)

        if part == last:
            def lossf(params, x, tokens):
                return transformer_stage_loss(params, x, tokens, cfg)
            fns = (None, jax.jit(jax.value_and_grad(lossf, argnums=(0, 1))))
        elif part == 0:
            def bwd(params, tokens, gy):
                _, vjp = jax.vjp(lambda pp: fwd(pp, tokens), params)
                return vjp(gy)[0]
            fns = (jax.jit(fwd), jax.jit(bwd))
        else:
            def bwd(params, x, gy):
                _, vjp = jax.vjp(fwd, params, x)
                return vjp(gy)
            fns = (jax.jit(fwd), jax.jit(bwd))
        self._jit[part] = fns
        return fns

    def _accumulate(self, part: int, gp) -> None:
        import jax
        acc = self._grads.get(part)
        self._grads[part] = gp if acc is None else \
            jax.tree.map(lambda a, b: a + b, acc, gp)

    # -- schedule ops (called by the resident loop) ----------------------

    def pipe_forward(self, part: int, mb: int, *vals):
        import jax.numpy as jnp
        import numpy as np
        part = int(part)
        if part == self.cfg.pp_stages - 1:
            # Last partition: forward is a stash (activations + targets);
            # loss + grads happen in one fused value_and_grad at backward.
            x, tokens = vals
            self._stash[(part, mb)] = (jnp.asarray(x), jnp.asarray(tokens))
            return None
        x = jnp.asarray(vals[0])
        jfwd, _ = self._fns(part)
        y = jfwd(self._params[part], x)
        self._stash[(part, mb)] = x
        return np.asarray(y)

    def pipe_backward(self, part: int, mb: int, *vals):
        import jax.numpy as jnp
        import numpy as np
        part = int(part)
        last = self.cfg.pp_stages - 1
        if part == last:
            x, tokens = self._stash.pop((part, mb))
            _, jloss = self._fns(part)
            loss, (gp, gx) = jloss(self._params[part], x, tokens)
            self._loss_sum += float(loss)
            self._loss_n += 1
            self._accumulate(part, gp)
            return np.asarray(gx)
        gy = jnp.asarray(vals[0])
        x = self._stash.pop((part, mb))
        _, jbwd = self._fns(part)
        if part == 0:
            self._accumulate(part, jbwd(self._params[part], x, gy))
            return None
        gp, gx = jbwd(self._params[part], x, gy)
        self._accumulate(part, gp)
        return np.asarray(gx)

    def _mean_grads(self, part: int):
        import jax
        m = max(1, int(self.cfg.num_microbatches))
        return jax.tree.map(lambda a: a / m, self._grads[part])

    def _metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self._loss_n:
            out["loss"] = self._loss_sum / self._loss_n
            self._loss_sum = 0.0
            self._loss_n = 0
        return out

    def pipe_apply(self) -> Dict[str, Any]:
        """End-of-step op (single-replica mode): optimizer-apply every
        owned partition on the accumulated microbatch-mean grads."""
        import optax
        for part in self.owned:
            if part not in self._grads:
                continue
            updates, self._opt[part] = self._tx.update(
                self._mean_grads(part), self._opt[part], self._params[part])
            self._params[part] = optax.apply_updates(self._params[part],
                                                     updates)
        self._grads.clear()
        return self._metrics()

    def pipe_report(self) -> Dict[str, Any]:
        """End-of-step op (DP-replica mode): keep grads for the driver's
        cross-replica average, report loss only."""
        return self._metrics()

    # -- DP grad exchange (classic task service, between steps) ----------

    def get_grads(self) -> Dict[int, Any]:
        import numpy as np
        import jax
        return {part: jax.tree.map(np.asarray, self._mean_grads(part))
                for part in self.owned if part in self._grads}

    def apply_external(self, avg_grads: Dict[int, Any]) -> None:
        import jax.numpy as jnp
        import jax
        import optax
        for part, g in avg_grads.items():
            part = int(part)
            g = jax.tree.map(jnp.asarray, g)
            updates, self._opt[part] = self._tx.update(
                g, self._opt[part], self._params[part])
            self._params[part] = optax.apply_updates(self._params[part],
                                                     updates)
        self._grads.clear()


def _default_tx_factory():
    import optax
    return optax.adamw(1e-3, weight_decay=0.01)


def _adamw_factory(learning_rate: float):
    import optax
    return optax.adamw(learning_rate, weight_decay=0.01)


class SleepStage:
    """Synthetic stage for schedule/transport benchmarks and tests: op
    cost is a pure sleep, so stages overlap even on a single-core host
    and measured efficiency isolates the SCHEDULE + channel overhead
    from jax compute."""

    def __init__(self, fwd_s: float = 0.0, bwd_s: float = 0.0):
        self.fwd_s = float(fwd_s)
        self.bwd_s = float(bwd_s)

    def ping(self) -> str:
        return "pong"

    def pipe_forward(self, part, mb, *vals):
        if self.fwd_s:
            time.sleep(self.fwd_s)
        return vals[0] if vals else mb

    def pipe_backward(self, part, mb, *vals):
        if self.bwd_s:
            time.sleep(self.bwd_s)
        return vals[0] if vals else mb

    def pipe_apply(self):
        return {}

    pipe_report = pipe_apply


# ---------------------------------------------------------------------------
# the compiled pipeline (driver side)
# ---------------------------------------------------------------------------

class CompiledPipeline:
    """A static microbatch schedule compiled onto cgraph channels.

    Model-agnostic: ``actors[a]`` hosts partitions ``{p : p % s == a}``
    and must expose ``forward_method(part, mb, *chan_vals)``,
    ``backward_method(part, mb, *chan_vals)`` and a zero-arg
    ``apply_method`` (the per-step done barrier). The driver feeds
    microbatch inputs to partition 0 (and targets to the last partition
    when ``feed_targets``), and reads one done payload per actor per
    step — which doubles as the efficiency probe (each stage reports its
    measured busy seconds)."""

    def __init__(self, actors: Sequence[Any], *, num_microbatches: int,
                 num_partitions: Optional[int] = None,
                 schedule: str = "1f1b",
                 forward_method: str = "pipe_forward",
                 backward_method: str = "pipe_backward",
                 apply_method: str = "pipe_apply",
                 feed_targets: bool = False,
                 channel_slots: Optional[int] = None,
                 slot_bytes: Optional[int] = None,
                 max_in_flight_steps: Optional[int] = None,
                 submit_timeout: float = 60.0):
        from ray_tpu import config
        rt = _runtime()
        if not hasattr(rt, "_actor_resolver"):
            raise RuntimeError(
                "CompiledPipeline requires cluster mode (resident stage "
                "loops live on actor workers; local mode has none)")
        s = len(actors)
        if s < 2:
            raise ValueError("a pipeline needs at least 2 stage actors")
        P = int(num_partitions or s)
        if P % s:
            raise ValueError(f"num_partitions {P} not a multiple of "
                             f"num_stages {s}")
        self._rt = rt
        self._gid = os.urandom(16)
        self.actors = list(actors)
        self.num_stages = s
        self.num_partitions = P
        self.num_chunks = P // s
        self.num_microbatches = int(num_microbatches)
        self.schedule = schedule
        self.feed_targets = bool(feed_targets)
        self._methods = (forward_method, backward_method, apply_method)
        self._slot_bytes = int(slot_bytes or config.get("pipeline_slot_bytes")
                               or config.get("cgraph_slot_bytes"))
        auto_slots = max(2, min(self.num_microbatches, P + 1))
        self._chan_slots = int(channel_slots or
                               config.get("pipeline_stage_channel_slots")
                               or auto_slots)
        self.max_in_flight_steps = int(
            max_in_flight_steps or config.get("pipeline_max_in_flight_steps"))
        self._submit_timeout = float(submit_timeout)
        self.bound = pipesched.bubble_bound(self.num_microbatches, s,
                                            self.num_chunks)
        self._lock = threading.RLock()
        self._next_step = 0
        self._read_step = 0
        self._results: Dict[int, dict] = {}
        self._poison_error: Optional[BaseException] = None
        self._torn_down = False
        self._installed: List[dict] = []       # per-actor {address, ...}
        self._done_readers: List[ShmChannelReader] = []
        self._feed_writers: List[Any] = []     # [input, targets?]
        self._actor_descs: List[dict] = []     # worker-owned rings (backstop)
        self._last_collect_t: Optional[float] = None
        try:
            self._build()
        except BaseException:  # noqa: BLE001 - cleanup then re-raise
            self._cleanup(best_effort=True)
            raise
        _live_graphs.add(self)

    # -- compilation -----------------------------------------------------

    def _build(self) -> None:
        rt = self._rt
        s, P, m = self.num_stages, self.num_partitions, self.num_microbatches
        fwd_m, bwd_m, apply_m = self._methods
        programs = pipesched.stage_programs(self.schedule, s, m,
                                            self.num_chunks)
        pipesched.validate_programs(programs, s, m, self.num_chunks)

        # Resolve stage placements (worker address + node daemon).
        daemons = {n["node_id"]: n["address"]
                   for n in rt.conductor.call("get_nodes")}
        places = []
        for h in self.actors:
            aid = h._rt_actor_id.binary()
            info = rt._actor_resolver.resolve(
                aid, timeout=self._submit_timeout) or {}
            if info.get("state") != "ALIVE":
                raise RuntimeError(
                    f"stage actor {aid.hex()} not ALIVE at compile time "
                    f"(state={info.get('state')!r})")
            if info["node_id"] not in daemons:
                raise RuntimeError(
                    f"no daemon known for node {info['node_id'].hex()}")
            places.append({"address": info["address"],
                           "node_id": info["node_id"],
                           "daemon": daemons[info["node_id"]]})

        def desc(owner: dict, nslots: int) -> dict:
            return {"id": make_channel_id(), "node_id": owner["node_id"],
                    "daemon": owner["daemon"], "nslots": nslots,
                    "slot_bytes": self._slot_bytes}

        owner = lambda p: places[pipesched.partition_owner(p, s)]
        driver = {"node_id": rt.node_id, "daemon": rt.daemon_address}
        n = self._chan_slots
        input_desc = desc(owner(0), n)
        targets_desc = desc(owner(P - 1), n) if self.feed_targets else None
        act_desc = {p: desc(owner(p), n) for p in range(1, P)}
        grad_desc = {p: desc(owner(p), n) for p in range(P - 1)}
        done_desc = [desc(driver, self.max_in_flight_steps)
                     for _ in range(s)]

        # Per-actor plans. Readers index into the actor's in_channels.
        plans = []
        for a, prog in enumerate(programs):
            in_channels: List[dict] = []
            index: Dict[bytes, int] = {}

            def rd(d: dict) -> int:
                i = index.get(d["id"])
                if i is None:
                    i = index[d["id"]] = len(in_channels)
                    in_channels.append(d)
                    self._actor_descs.append(d)
                return i

            ops: List[dict] = []
            for op in prog:
                p, mb = op.part, op.mb
                if op.kind == "F":
                    reads = [[rd(input_desc if p == 0 else act_desc[p]),
                              m, mb]]
                    if p == P - 1 and self.feed_targets:
                        reads.append([rd(targets_desc), m, mb])
                    writes = ([[act_desc[p + 1], m, mb]] if p < P - 1
                              else [])
                    method = fwd_m
                else:
                    reads = ([[rd(grad_desc[p]), m, mb]] if p < P - 1
                             else [])
                    writes = [[grad_desc[p - 1], m, mb]] if p > 0 else []
                    method = bwd_m
                flow = ("s" if (op.kind, p) == ("F", 0) else
                        "f" if (op.kind, p) == ("B", 0) else "t")
                ops.append({"method": method, "const": [p, mb],
                            "reads": reads, "writes": writes,
                            "ev": {"stage": a, "part": p, "mb": mb,
                                   "kind": op.kind, "flow": flow}})
            # Per-step done barrier: every actor ends its program with the
            # apply/report op writing its done ring (stride 1).
            ops.append({"method": apply_m, "const": [], "reads": [],
                        "writes": [[done_desc[a], 1, 0]], "ev": None,
                        "done": True})
            plans.append({"mode": "schedule", "stage": a,
                          "microbatches": m, "slot_bytes": self._slot_bytes,
                          "nslots": n, "in_channels": in_channels,
                          "ops": ops})

        # Driver-owned done rings exist before any loop can write them.
        for d in done_desc:
            self._done_readers.append(
                ShmChannelReader(rt.store, d["id"], d["nslots"],
                                 d["slot_bytes"]))

        from ray_tpu.cluster.protocol import get_client
        for a, plan in enumerate(plans):
            resp = get_client(places[a]["address"]).call(
                "install_cgraph_loop", graph_id=self._gid, plan=plan,
                _timeout=self._submit_timeout)
            if not resp or not resp.get("ok"):
                raise RuntimeError(
                    f"pipeline loop install failed on stage {a}: {resp!r}")
            self._installed.append(places[a])

        def feed_writer(d: dict):
            if d["node_id"] == rt.node_id:
                return ShmChannelWriter(rt.store, d["id"])
            return RpcChannelWriter(d["id"], d["daemon"])

        self._feed_writers.append(feed_writer(input_desc))
        if targets_desc is not None:
            self._feed_writers.append(feed_writer(targets_desc))
        self._last_part_actor = pipesched.partition_owner(P - 1, s)

    # -- execution -------------------------------------------------------

    def _check_alive_locked(self) -> None:
        if self._torn_down:
            raise RuntimeError("pipeline was torn down")
        if self._poison_error is not None:
            raise RuntimeError(
                "pipeline is poisoned by a prior failure "
                f"({self._poison_error!r}); teardown() and rebuild") \
                from self._poison_error

    def submit(self, microbatches: Sequence[Any],
               targets: Optional[Sequence[Any]] = None,
               timeout: Optional[float] = None) -> int:
        """Feed one training step's microbatch stream; returns the step
        index. Blocks when ``max_in_flight_steps`` are outstanding."""
        m = self.num_microbatches
        if len(microbatches) != m:
            raise ValueError(f"expected {m} microbatches, "
                             f"got {len(microbatches)}")
        if self.feed_targets and (targets is None or len(targets) != m):
            raise ValueError(f"expected {m} target microbatches")
        deadline = time.monotonic() + (timeout or self._submit_timeout)
        with self._lock:
            self._check_alive_locked()
            while self._next_step - self._read_step >= \
                    self.max_in_flight_steps:
                self._collect_locked(self._read_step,
                                     deadline - time.monotonic())
            step = self._next_step
            self._next_step += 1
            try:
                feeds = ([microbatches, targets] if self.feed_targets
                         else [microbatches])
                for w, vals in zip(self._feed_writers, feeds):
                    for mb in range(m):
                        blob, flags = _encode_value(
                            vals[mb], self._slot_bytes, self._rt.plane)
                        _write_slot(w, step * m + mb, blob, flags,
                                    timeout=max(0.05, deadline -
                                                time.monotonic()),
                                    role="driver")
            except BaseException as e:  # noqa: BLE001 - poison the pipeline then re-raise
                if self._poison_error is None:
                    self._poison_error = e
                raise
        return step

    def _collect_locked(self, step: int, timeout: float) -> dict:
        """Drain every actor's done ring for ``step``: readiness-polling
        so poison from ANY stage surfaces immediately even while another
        stage is still wedged mid-schedule."""
        from ray_tpu.core.exceptions import GetTimeoutError
        if step in self._results:
            return self._results.pop(step)
        deadline = time.monotonic() + timeout
        payloads: List[Optional[dict]] = [None] * len(self._done_readers)
        remaining = set(range(len(self._done_readers)))
        while remaining:
            progressed = False
            for i in sorted(remaining):
                if not self._done_readers[i].ready(step):
                    continue
                blob, flags = _read_slot(self._done_readers[i], step, 1.0)
                if flags & FLAG_POISON:
                    err = _decode_value(blob, flags & ~FLAG_POISON,
                                        self._rt.plane)
                    if not isinstance(err, BaseException):
                        err = RuntimeError(f"pipeline poisoned: {err!r}")
                    self._poison_error = err
                    raise err
                payloads[i] = _decode_value(blob, flags, self._rt.plane)
                remaining.discard(i)
                progressed = True
            if not remaining:
                break
            if time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"pipeline step {step} done barrier not reached within "
                    f"{timeout:.1f}s (stages pending: {sorted(remaining)})")
            if not progressed:
                time.sleep(0.0005)
        self._read_step = max(self._read_step, step + 1)

        now = time.perf_counter()
        wall = (now - self._last_collect_t
                if self._last_collect_t is not None else None)
        self._last_collect_t = now
        busy = [float(p.get("busy_s", 0.0)) for p in payloads if p]
        eff = (sum(busy) / (self.num_stages * wall)
               if wall and wall > 0 else None)
        merged = dict(payloads[self._last_part_actor] or {})
        merged.pop("busy_s", None)
        merged.pop("stage", None)
        merged["stages"] = payloads
        merged["wall_s"] = wall
        merged["busy_s"] = busy
        merged["efficiency"] = eff
        merged["bound"] = self.bound
        _events().emit("pipeline.step", self._gid.hex()[:16],
                       value=float(wall or 0.0),
                       attrs={"step": step, "stages": self.num_stages,
                              "microbatches": self.num_microbatches,
                              "schedule": self.schedule,
                              "efficiency": eff})
        return merged

    def collect(self, step: Optional[int] = None,
                timeout: Optional[float] = None) -> dict:
        from ray_tpu import config
        with self._lock:
            self._check_alive_locked()
            if step is None:
                step = self._read_step
            if step >= self._next_step:
                raise ValueError(f"step {step} was never submitted")
            return self._collect_locked(
                step, timeout or config.get("pipeline_step_timeout_s"))

    def step(self, microbatches: Sequence[Any],
             targets: Optional[Sequence[Any]] = None,
             timeout: Optional[float] = None) -> dict:
        t = self.submit(microbatches, targets, timeout=timeout)
        return self.collect(t, timeout=timeout)

    # -- teardown --------------------------------------------------------

    def teardown(self) -> None:
        """Uninstall the stage loops, delete every ring segment, restore
        classic actor task service. Idempotent."""
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        self._cleanup(best_effort=True)
        _live_graphs.discard(self)

    def _cleanup(self, best_effort: bool = False) -> None:
        from ray_tpu.cluster.protocol import get_client
        for place in self._installed:
            try:
                get_client(place["address"]).call(
                    "teardown_cgraph_loop", graph_id=self._gid,
                    _timeout=20.0)
            except Exception:
                if not best_effort:
                    raise
        for w in self._feed_writers:
            try:
                w.close()
            except Exception:
                pass
        for r in self._done_readers:
            try:
                r.close()
            except Exception:
                pass
        # Backstop: a CRASHED worker cannot delete the rings it owns; its
        # node daemon still can (idempotent for rings already gone).
        for d in self._actor_descs:
            try:
                get_client(d["daemon"]).call("delete_object", oid=d["id"],
                                             _timeout=5.0)
            except Exception:
                pass
        self._installed = []
        self._feed_writers = []
        self._done_readers = []
        self._actor_descs = []

    def __repr__(self):
        return (f"CompiledPipeline({self._gid.hex()[:8]}, "
                f"stages={self.num_stages}x{self.num_chunks}, "
                f"m={self.num_microbatches}, schedule={self.schedule!r})")


# ---------------------------------------------------------------------------
# the trainer
# ---------------------------------------------------------------------------

class PipelineTrainer:
    """MPMD pipeline-parallel LM trainer: DP replicas of a PP pipeline.

    ``num_stages`` actors each host ``num_chunks`` layer partitions
    (``cfg.pp_stages`` must equal their product; it is set for you when
    left at 1). With ``dp_replicas > 1`` the whole pipeline is cloned;
    each step the driver averages the replicas' microbatch-mean grads
    over classic task RPCs and broadcasts one optimizer apply — the
    schedule then ends in ``pipe_report`` instead of the in-loop
    ``pipe_apply``."""

    def __init__(self, config, *, num_stages: int = 2,
                 num_microbatches: int = 4, schedule: str = "1f1b",
                 num_chunks: int = 1, dp_replicas: int = 1,
                 learning_rate: float = 1e-3,
                 tx_factory: Optional[Callable[[], Any]] = None,
                 seed: int = 0, num_cpus_per_stage: float = 1.0,
                 channel_slots: Optional[int] = None,
                 max_in_flight_steps: Optional[int] = None):
        if num_stages < 2:
            raise ValueError("PipelineTrainer needs num_stages >= 2")
        P = num_stages * num_chunks
        if config.pp_stages == 1:
            config = dataclasses.replace(config, pp_stages=P)
        if config.pp_stages != P:
            raise ValueError(f"cfg.pp_stages={config.pp_stages} != "
                             f"num_stages*num_chunks={P}")
        if config.n_layers % P:
            raise ValueError(f"n_layers={config.n_layers} not divisible "
                             f"by {P} partitions")
        if config.tied_embeddings:
            raise ValueError("MPMD pipeline requires tied_embeddings=False")
        if num_chunks > 1 and schedule != "interleaved_1f1b":
            raise ValueError("num_chunks > 1 requires the "
                             "interleaved_1f1b schedule")
        self.config = dataclasses.replace(
            config, num_microbatches=num_microbatches)
        self.num_stages = num_stages
        self.num_chunks = num_chunks
        self.num_microbatches = num_microbatches
        self.schedule = schedule
        self.dp_replicas = int(dp_replicas)
        self.seed = seed
        self._tx_factory = tx_factory or _partial_adamw(learning_rate)
        self._num_cpus = num_cpus_per_stage
        self._channel_slots = channel_slots
        self._max_in_flight = max_in_flight_steps
        self._groups: List[List[Any]] = []    # [replica][stage] handles
        self._pipes: List[CompiledPipeline] = []
        self._step = 0
        self.last_metrics: Optional[dict] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "PipelineTrainer":
        import jax
        import numpy as np
        import ray_tpu
        from ray_tpu.models.transformer import (transformer_init,
                                                transformer_partition_params)
        if self._pipes:
            return self
        cfg = self.config
        P = cfg.pp_stages
        params = transformer_init(jax.random.PRNGKey(self.seed), cfg)
        part_params = [
            jax.tree.map(np.asarray,
                         transformer_partition_params(params, cfg, p))
            for p in range(P)]
        actor_cls = ray_tpu.remote(PipelineStageActor)
        apply_m = "pipe_apply" if self.dp_replicas == 1 else "pipe_report"
        for _ in range(self.dp_replicas):
            stages = []
            for a in range(self.num_stages):
                owned = list(range(a, P, self.num_stages))
                stages.append(actor_cls.options(
                    num_cpus=self._num_cpus).remote(
                        cfg, owned, self._tx_factory))
            ray_tpu.get([h.load_partition.remote(p, part_params[p])
                         for a, h in enumerate(stages)
                         for p in range(a, P, self.num_stages)])
            self._groups.append(stages)
            self._pipes.append(CompiledPipeline(
                stages, num_microbatches=self.num_microbatches,
                num_partitions=P, schedule=self.schedule,
                apply_method=apply_m, feed_targets=True,
                channel_slots=self._channel_slots,
                max_in_flight_steps=self._max_in_flight))
        return self

    def shutdown(self) -> None:
        import ray_tpu
        for pipe in self._pipes:
            try:
                pipe.teardown()
            except Exception:
                pass
        for stages in self._groups:
            for h in stages:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
        self._pipes = []
        self._groups = []

    # -- training --------------------------------------------------------

    def _split(self, tokens) -> List[List[Any]]:
        import numpy as np
        tokens = np.asarray(tokens)
        R, m = self.dp_replicas, self.num_microbatches
        if tokens.shape[0] % (R * m):
            raise ValueError(
                f"batch size {tokens.shape[0]} not divisible by "
                f"dp_replicas*num_microbatches = {R * m}")
        shards = np.split(tokens, R, axis=0)
        return [[np.ascontiguousarray(x) for x in np.split(s, m, axis=0)]
                for s in shards]

    def step(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """One pipelined training step over ``batch["tokens"]`` [B, S]
        (B divisible by dp_replicas * num_microbatches)."""
        if not self._pipes:
            self.start()
        per_replica = self._split(batch["tokens"])
        steps = [pipe.submit(mbs, mbs)
                 for pipe, mbs in zip(self._pipes, per_replica)]
        results = [pipe.collect(t)
                   for pipe, t in zip(self._pipes, steps)]
        if self.dp_replicas > 1:
            self._dp_sync()
        losses = [r.get("loss") for r in results if r.get("loss") is not None]
        metrics = {
            "step": self._step,
            "loss": float(sum(losses) / len(losses)) if losses else None,
            "efficiency": results[0].get("efficiency"),
            "bound": results[0].get("bound"),
            "wall_s": results[0].get("wall_s"),
            "busy_s": results[0].get("busy_s"),
        }
        self._step += 1
        self.last_metrics = metrics
        return metrics

    def _dp_sync(self) -> None:
        """Average microbatch-mean grads across replicas per stage, then
        broadcast one optimizer apply (classic task RPCs: the resident
        loops are quiescent between the done barrier and the next
        submit)."""
        import numpy as np
        import jax
        import ray_tpu
        for a in range(self.num_stages):
            grads = ray_tpu.get(
                [g[a].get_grads.remote() for g in self._groups])
            avg: Dict[int, Any] = {}
            for part in grads[0]:
                avg[part] = jax.tree.map(
                    lambda *xs: np.mean(np.stack(xs), axis=0),
                    *[g[part] for g in grads])
            ray_tpu.get([g[a].apply_external.remote(avg)
                         for g in self._groups])

    def train(self, batches: Sequence[Dict[str, Any]]) -> List[dict]:
        self.start()
        return [self.step(b) for b in batches]


def _partial_adamw(lr: float):
    from functools import partial
    return partial(_adamw_factory, lr)
