"""Trainers: BaseTrainer / DataParallelTrainer / JaxTrainer.

Role parity: python/ray/train/base_trainer.py:554 (BaseTrainer.fit),
data_parallel_trainer.py:56 (DataParallelTrainer -> BackendExecutor ->
WorkerGroup), torch/torch_trainer.py:15 (framework trainer). The reference
routes fit() through a single-trial Tune run (base_trainer.py:579); here
fit() drives the BackendExecutor directly, and ray_tpu.tune.Tuner wraps a
trainer the same way when sweeping.

TPU-first: the framework trainer is JaxTrainer — the user loop builds a
mesh from ScalingConfig.mesh and a pjit step; on multi-host gangs
JaxBackend has already done jax.distributed.initialize, so
jax.devices() spans the slice and the same pjit code scales (SURVEY §3.4).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.backend_executor import (Backend, BackendExecutor,
                                            JaxBackend, TrainingFailedError)


_CKPT_MARKER = "_COMPLETE"


def _find_restorable_checkpoint(trial_dir: str) -> Optional[str]:
    """Newest COMPLETE persisted checkpoint, surviving a crash at any
    point of _persist_checkpoint's swap: prefer checkpoint_latest, then
    .tmp (newer but unswapped — complete iff marked), then .old."""
    final = os.path.join(trial_dir, "checkpoint_latest")
    for cand in (final, final + ".tmp", final + ".old"):
        if os.path.isdir(cand) and \
                os.path.exists(os.path.join(cand, _CKPT_MARKER)):
            return cand
    # Pre-marker layouts (or externally written dirs): accept a bare
    # checkpoint_latest rather than silently restarting from scratch.
    if os.path.isdir(final):
        return final
    return None


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    @classmethod
    def restore(cls, path: str) -> "BaseTrainer":
        """Rebuild a trainer from a previous run's trial dir and resume
        from its latest persisted checkpoint (parity:
        base_trainer.py:567-579 BaseTrainer.restore — experiment-level
        resume after DRIVER death, vs. the in-fit elastic restart that
        only survives worker death)."""
        from ray_tpu.core import serialization
        spec_path = os.path.join(path, "trainer.pkl")
        if not os.path.exists(spec_path):
            raise FileNotFoundError(
                f"no trainer state found under {path!r} (trainer.pkl "
                "missing — was fit() ever started here?)")
        with open(spec_path, "rb") as f:
            trainer = serialization.loads(f.read())
        ckpt_dir = _find_restorable_checkpoint(path)
        if ckpt_dir is not None:
            trainer.resume_from_checkpoint = Checkpoint.from_directory(
                ckpt_dir)
        return trainer

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(os.path.join(path, "trainer.pkl"))

    def _save_spec(self, trial_dir: str) -> None:
        """Persist this trainer's construction so restore() can rebuild it
        in a fresh process (written once, before training starts)."""
        from ray_tpu.core import serialization
        spec_path = os.path.join(trial_dir, "trainer.pkl")
        if not os.path.exists(spec_path):
            tmp = spec_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(serialization.dumps(self))
            os.replace(tmp, spec_path)

    @staticmethod
    def _persist_checkpoint(trial_dir: str, ckpt: Checkpoint) -> None:
        """Write the latest checkpoint under the trial dir so a dead
        driver can resume from disk, not just from memory. Directory swaps
        cannot be single-rename-atomic; every intermediate state is
        covered by a COMPLETE marker + the restore fallback chain
        (_find_restorable_checkpoint): .tmp carries the marker only once
        fully written, .old keeps the previous complete checkpoint until
        the new one is in place."""
        final = os.path.join(trial_dir, "checkpoint_latest")
        tmp, old = final + ".tmp", final + ".old"
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        ckpt.to_directory(tmp)
        with open(os.path.join(tmp, _CKPT_MARKER), "w") as f:
            f.write("1")
        shutil.rmtree(old, ignore_errors=True)
        if os.path.isdir(final):
            os.replace(final, old)
        os.replace(tmp, final)
        shutil.rmtree(old, ignore_errors=True)

    def as_trainable(self) -> Callable[[dict], Result]:
        """A Tune-compatible trainable closing over this trainer (parity:
        base_trainer.py:666 as_trainable)."""
        trainer = self

        def trainable(config: dict) -> Result:
            import copy
            t = copy.copy(trainer)
            merged = dict(getattr(t, "train_loop_config", None) or {})
            merged.update(config)
            t.train_loop_config = merged
            return t.fit()

        return trainable


class DataParallelTrainer(BaseTrainer):
    """N identical workers running one loop (parity:
    data_parallel_trainer.py:56)."""

    _backend_cls: Callable[[], Backend] = Backend

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 backend: Optional[Backend] = None,
                 datasets: Optional[dict] = None):
        super().__init__(scaling_config=scaling_config, run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend = backend or self._backend_cls()
        # name -> ray_tpu.data.Dataset, split per-rank at fit() and exposed
        # in workers via session.get_dataset_shard (air session parity).
        self.datasets = datasets or {}

    def fit(self) -> Result:
        cfg = self.run_config
        trial_dir = os.path.join(
            cfg.storage_path or tempfile.gettempdir(),
            cfg.name or "rtpu_train")
        os.makedirs(trial_dir, exist_ok=True)
        self._save_spec(trial_dir)
        stop = cfg.stop or {}
        failure = cfg.failure_config or FailureConfig()
        attempts = 0
        num_workers = self.scaling_config.num_workers
        while True:
            executor = BackendExecutor(
                self.backend, num_workers,
                self.scaling_config.worker_resources(),
                self.scaling_config.placement_strategy,
                slice_topology=self.scaling_config.topology)
            state = {"last_metrics": {}, "last_checkpoint":
                     self.resume_from_checkpoint, "history": []}

            def on_report(merged):
                state["last_metrics"] = merged["metrics"]
                state["history"].append(merged["metrics"])
                if merged["checkpoint"] is not None:
                    state["last_checkpoint"] = merged["checkpoint"]
                    try:
                        self._persist_checkpoint(trial_dir,
                                                 merged["checkpoint"])
                    except Exception:
                        pass  # persistence is best-effort; in-memory
                        # state still drives this fit()'s own restarts
                for key, bound in stop.items():
                    if key == "training_iteration":
                        if merged["iteration"] >= bound:
                            return "stop"
                    elif merged["metrics"].get(key) is not None and \
                            merged["metrics"][key] >= bound:
                        return "stop"
                return None

            try:
                # First formation waits the full window (nodes may still be
                # joining). On an elastic RESTART capacity just shrank, and
                # the worker count was planned from a membership view that
                # can lag the failure — an infeasible gang should fail fast
                # and re-plan against the settled cluster, not park on the
                # placement timeout.
                executor.start(ready_timeout=15.0 if attempts else 120.0)
                executor.run(self.train_loop_per_worker,
                             self.train_loop_config, on_report,
                             trial_dir=trial_dir,
                             checkpoint=state["last_checkpoint"],
                             datasets=self.datasets)
                return Result(metrics=state["last_metrics"],
                              checkpoint=state["last_checkpoint"],
                              metrics_history=state["history"],
                              config=dict(self.train_loop_config),
                              path=trial_dir)
            except TrainingFailedError as e:
                attempts += 1
                if failure.max_failures != -1 and \
                        attempts > failure.max_failures:
                    return Result(metrics=state["last_metrics"],
                                  checkpoint=state["last_checkpoint"],
                                  metrics_history=state["history"],
                                  error=e,
                                  config=dict(self.train_loop_config),
                                  path=trial_dir)
                # elastic restart from the last checkpoint (SURVEY §5:
                # a lost host kills the XLA program; recovery = re-form
                # the gang + checkpoint restore, not per-task retry)
                self.resume_from_checkpoint = state["last_checkpoint"]
                if failure.elastic:
                    # Mesh-shrink: re-plan the gang against the SURVIVING
                    # cluster. A smaller world_size resumes from the last
                    # checkpoint now instead of parking on a lost host
                    # (SURVEY §7 hard part: re-form a smaller mesh).
                    num_workers = self._feasible_workers(
                        num_workers, failure.min_workers)
            finally:
                executor.shutdown()

    def _feasible_workers(self, want: int, floor: int,
                          settle_timeout: float = 30.0) -> int:
        """How many workers the LIVE cluster can host right now. Waits
        briefly for membership to settle (the dead node's health timeout)
        whenever even ``floor`` workers don't fit yet."""
        import math
        import time as _time

        import ray_tpu as rt
        res = self.scaling_config.worker_resources()
        deadline = _time.monotonic() + settle_timeout
        from ray_tpu.cluster.protocol import get_client
        while True:
            slots = 0
            assessable = False
            try:
                for n in rt.nodes():
                    if not n["Alive"] or ":" not in str(n.get("address", "")):
                        continue  # local-mode runtime: nothing to re-plan
                    assessable = True
                    # The conductor's health view lags a crash by its
                    # timeout; a direct daemon ping settles liveness NOW (a
                    # dead daemon refuses instantly; timeout=1.0 bounds the
                    # CONNECT too, so a power-failed host can't park the
                    # re-plan on the OS SYN-retry clock).
                    try:
                        get_client(n["address"], timeout=1.0).call(
                            "ping", _timeout=1.0)
                    except Exception:
                        continue
                    cap = min((n["Resources"].get(k, 0.0) / v
                               for k, v in res.items() if v > 0),
                              default=0.0)
                    slots += int(math.floor(cap))
            except Exception:
                slots = 0
            if not assessable:
                return want
            if slots >= floor or _time.monotonic() >= deadline:
                return max(floor, min(want, slots))
            _time.sleep(0.5)


class JaxTrainer(DataParallelTrainer):
    """The framework trainer (role of TorchTrainer, torch_trainer.py:15)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 distributed: bool = True, **kwargs):
        super().__init__(train_loop_per_worker,
                         backend=JaxBackend(distributed=distributed),
                         **kwargs)


class TorchTrainer(DataParallelTrainer):
    """torch loops in the gang (parity: torch/torch_trainer.py:15): a gloo
    process group spans the workers; train.torch_utils.prepare_model /
    prepare_data_loader give DDP + per-rank sharding. Host-CPU only here —
    accelerator math is the jax stack's job (JaxTrainer)."""

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        from ray_tpu.train.backend_executor import TorchBackend
        super().__init__(train_loop_per_worker, backend=TorchBackend(),
                         **kwargs)
