"""Learner -> workers weight broadcast over the collective object plane.

Role parity: python/ray/util/collective broadcast used by Train/RLlib for
weight sync — the learner publishes one weight object and a collective
moves it to every worker host, instead of each worker pulling its own
copy through the learner's NIC (N serial transfers for N workers).

r16 wiring: ``rt.put`` of an array value already takes the RTAR zero-copy
fast path; ``broadcast_to_actors`` then pre-places the object on every
distinct node hosting a consumer actor via the object plane's broadcast
tree (ObjectPlane.broadcast_object — rounds of coordinated pulls, each
fresh holder serving the next wave). Consumers ``rt.get`` the returned
ref and hit their LOCAL store: a read-only array view over pinned shm,
no copy, no network.

Everything here is best-effort: a failed (or skipped) broadcast leaves
consumers on the classic directory-driven pull path — slower, never
wrong.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

logger = logging.getLogger(__name__)


def member_nodes(actors, conductor, timeout: float = 30.0) -> List[dict]:
    """Distinct live nodes hosting ``actors``, as broadcast member
    descriptors ({"node_id", "address"} of each node's daemon)."""
    infos = conductor.call(
        "get_actor_infos",
        actor_ids=[a.actor_id.binary() for a in actors],
        wait_alive_timeout=timeout)
    node_ids = {i["node_id"] for i in infos if i.get("node_id")}
    return [{"node_id": n["node_id"], "address": n["address"]}
            for n in conductor.call("get_nodes")
            if n.get("alive") and n["node_id"] in node_ids]


def broadcast_to_actors(value: Any, actors, timeout: float = 30.0):
    """Put ``value`` once and pre-place it on every node hosting one of
    ``actors``; returns the ObjectRef to pass to the consumers. The
    transfer rides the object plane's broadcast tree when the runtime has
    one (cluster mode, value above array_bcast_min_bytes); otherwise the
    ref alone is returned and consumers pull on first get."""
    import ray_tpu as rt
    from ray_tpu.core.api import _global_runtime

    ref = rt.put(value)
    runtime = _global_runtime()
    plane = getattr(runtime, "plane", None)
    conductor = getattr(runtime, "conductor", None)
    if plane is None or conductor is None or not actors:
        return ref  # local mode: every consumer shares this store anyway
    try:
        members = member_nodes(actors, conductor, timeout=timeout)
        if members:
            plane.broadcast_object(ref.id, members)
    except Exception:  # noqa: BLE001 - pre-placement only, never fatal
        logger.warning("weight broadcast pre-placement failed; consumers "
                       "fall back to on-demand pulls", exc_info=True)
    return ref


def fetch_weights(ref, timeout: Optional[float] = 60.0):
    """Consumer-side half: resolve a broadcast ref to a (read-only) value
    from the local store — present for symmetry and mockability; today it
    is exactly ``rt.get``."""
    import ray_tpu as rt
    return rt.get(ref, timeout=timeout)
