"""torch conveniences for TorchTrainer loops.

Role parity: python/ray/train/torch/train_loop_utils.py — prepare_model
(DDP wrap), prepare_data_loader (DistributedSampler), get_device. CPU/gloo
only in this framework: torch is the host-side data/eval path; accelerator
math belongs to the jax/pjit stack (JaxTrainer)."""

from __future__ import annotations

from typing import Any


def get_device():
    import torch
    return torch.device("cpu")


def prepare_model(model):
    """Wrap in DistributedDataParallel when a process group is live."""
    import torch.distributed as dist
    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Re-build the loader with a DistributedSampler sharding per rank."""
    import torch.distributed as dist
    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler
    sampler = DistributedSampler(data_loader.dataset)
    return DataLoader(data_loader.dataset,
                      batch_size=data_loader.batch_size,
                      sampler=sampler,
                      num_workers=0,
                      collate_fn=data_loader.collate_fn,
                      drop_last=data_loader.drop_last)
