"""Compiled train-step factories: model + mesh + optax -> sharded pjit step.

The GSPMD recipe (scaling-book): place params with explicit NamedShardings
(logical axes -> mesh axes), let jit propagate shardings through optimizer
state and activations, and let XLA insert the DP psum / FSDP
all-gather+reduce-scatter / TP collectives. This replaces the reference's
entire process-group + DDP/FSDP-wrapper surface (reference
python/ray/train/torch/config.py:113, train_loop_utils.py:23-96) with
compilation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.resnet import resnet50, resnet_loss
from ray_tpu.models.transformer import (TransformerConfig, transformer_init,
                                        transformer_logical_axes,
                                        transformer_loss)
from ray_tpu.parallel.sharding import (DEFAULT_RULES, LogicalRules,
                                       batch_sharding, pytree_shardings,
                                       replicated, shard_pytree)


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any  # scalar int32 array


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(*c),
)


def make_lm_train_step(cfg: TransformerConfig, mesh: Mesh,
                       tx: Optional[optax.GradientTransformation] = None,
                       rules: LogicalRules = DEFAULT_RULES,
                       learning_rate: float = 3e-4):
    """Returns (init_fn(key) -> TrainState on-mesh,
               step_fn(state, batch) -> (state, metrics) jitted)."""
    if tx is None:
        tx = optax.adamw(learning_rate, weight_decay=0.01)
    axes = transformer_logical_axes(cfg)

    def init_fn(key) -> TrainState:
        params = transformer_init(key, cfg)
        params = shard_pytree(params, mesh, axes, rules)
        # jit(tx.init): zeros_like(p) inherits p's sharding, so optimizer
        # moments land sharded exactly like their params (ZeRO under fsdp).
        opt_state = jax.jit(tx.init)(params)
        return TrainState(params, opt_state,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated(mesh)))

    def loss_fn(params, batch):
        return transformer_loss(params, batch, cfg, mesh=mesh)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, batch) -> Tuple[TrainState, dict]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss, "grad_norm": gnorm, "step": state.step + 1})

    def place_batch(batch):
        return jax.tree.map(
            lambda x: jax.device_put(x, batch_sharding(mesh, x.ndim, rules)),
            batch)

    return init_fn, step_fn, place_batch


def make_resnet_train_step(mesh: Mesh, *, num_classes: int = 1000,
                           image_size: int = 224,
                           tx: Optional[optax.GradientTransformation] = None,
                           learning_rate: float = 0.1,
                           rules: LogicalRules = DEFAULT_RULES):
    """ResNet-50 data-parallel train step: params replicated, batch sharded
    over (dp, fsdp); XLA inserts the gradient psum (DDP-equivalent)."""
    if tx is None:
        tx = optax.sgd(learning_rate, momentum=0.9, nesterov=True)
    model = resnet50(num_classes)

    def init_fn(key) -> TrainState:
        variables = model.init(
            key, jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            train=True)
        variables = jax.device_put(variables, replicated(mesh))
        opt_state = jax.jit(tx.init)(variables["params"])
        return TrainState(variables, opt_state,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated(mesh)))

    def loss_fn(params, batch_stats, images, labels):
        logits, new_stats = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        return resnet_loss(logits, labels), (logits, new_stats["batch_stats"])

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, batch) -> Tuple[TrainState, dict]:
        variables = state.params
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(variables["params"],
                                   variables["batch_stats"],
                                   batch["image"], batch["label"])
        updates, opt_state = tx.update(grads, state.opt_state,
                                       variables["params"])
        new_params = optax.apply_updates(variables["params"], updates)
        acc = (logits.argmax(-1) == batch["label"]).mean()
        new_vars = {"params": new_params, "batch_stats": new_stats}
        return (TrainState(new_vars, opt_state, state.step + 1),
                {"loss": loss, "accuracy": acc})

    def place_batch(batch):
        return jax.tree.map(
            lambda x: jax.device_put(x, batch_sharding(mesh, x.ndim, rules)),
            batch)

    return init_fn, step_fn, place_batch


def make_vit_train_step(cfg, mesh: Mesh, *,
                        tx: Optional[optax.GradientTransformation] = None,
                        learning_rate: float = 3e-4,
                        rules: LogicalRules = DEFAULT_RULES):
    """ViT train step on the shared transformer substrate: encoder layers
    shard by the SAME logical-axis rules as the LM (fsdp/tp apply), batch
    over the data axes; gradient psum inserted by XLA."""
    from ray_tpu.models.vit import vit_init, vit_loss

    if tx is None:
        tx = optax.adamw(learning_rate, weight_decay=0.05)
    enc = cfg.encoder_config()

    def init_fn(key) -> TrainState:
        params = vit_init(key, cfg)
        layer_axes = transformer_logical_axes(enc)["layers"]
        axes = {
            "patch_proj": (None, "embed"),
            "cls": (None, None, "embed"),
            "pos": (None, None, "embed"),
            "layers": layer_axes,
            "ln_f": (None,),
            "head": ("embed", None),
        }
        params = shard_pytree(params, mesh, axes, rules)
        opt_state = jax.jit(tx.init)(params)
        return TrainState(params, opt_state,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated(mesh)))

    def loss_fn(params, batch):
        return vit_loss(params, batch, cfg, mesh=mesh)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, batch) -> Tuple[TrainState, dict]:
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss, "accuracy": acc})

    def place_batch(batch):
        return jax.tree.map(
            lambda x: jax.device_put(x, batch_sharding(mesh, x.ndim, rules)),
            batch)

    return init_fn, step_fn, place_batch
