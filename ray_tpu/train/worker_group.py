"""WorkerGroup: gang of train-worker actors.

Role parity: python/ray/train/_internal/worker_group.py:92 (WorkerGroup) and
:17 (RayTrainWorker) — N actors, one per host slot, placed in one placement
group; ``execute`` fans a function to all workers; ``start_training`` runs
the user loop in a thread per worker with an active session.

TPU-first delta: workers are *gang-scheduled* (all bundles of one PG, with
STRICT_PACK keeping a pjit gang on one ICI slice), because a multi-host XLA
program needs every process to enter the same computation (SURVEY.md §7
"SPMD vs actor impedance").
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional


class RayTrainWorker:
    """Actor hosting one rank of the training gang."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self._session = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[dict] = None
        self._done = threading.Event()

    def setup_env(self, env: Dict[str, str]) -> bool:
        import os
        os.environ.update(env)
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def start_training(self, loop_fn: Callable, config: dict,
                      trial_dir: str = "", checkpoint=None,
                      dataset_shards=None) -> bool:
        from ray_tpu.air import session as session_mod
        sess = session_mod._Session(
            self.world_rank, self.world_size, self.local_rank,
            trial_dir=trial_dir, config=config, checkpoint=checkpoint,
            dataset_shards=dataset_shards)
        self._session = sess
        self._done.clear()
        self._error = None

        def run():
            session_mod._set_session(sess)
            try:
                if _accepts_config(loop_fn):
                    loop_fn(config)
                else:
                    loop_fn()
            except StopIteration:
                pass
            except BaseException:  # noqa: BLE001 - shipped to the driver
                self._error = {"traceback": traceback.format_exc()}
            finally:
                session_mod._set_session(None)
                with sess.report_event:
                    self._done.set()
                    sess.report_event.notify_all()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"train-rank{self.world_rank}")
        self._thread.start()
        return True

    def next_report(self, index: int, timeout: float = 10.0):
        """Block until report[index] exists (or the loop finished)."""
        sess = self._session
        if sess is None:
            return {"status": "no_session"}
        import time
        deadline = time.monotonic() + timeout
        with sess.report_event:
            while len(sess.reports) <= index:
                if self._done.is_set():
                    if self._error:
                        return {"status": "error", **self._error}
                    return {"status": "finished"}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"status": "pending"}
                sess.report_event.wait(remaining)
            r = sess.reports[index]
            return {"status": "report", "metrics": r["metrics"],
                    "checkpoint": r["checkpoint"],
                    "iteration": r["iteration"]}

    def request_stop(self) -> bool:
        if self._session is not None:
            self._session.stop_requested = True
        return True

    def shutdown_worker(self) -> bool:
        return True


def _identity(x):
    return x


def _accepts_config(fn: Callable) -> bool:
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


class WorkerGroup:
    """Driver-side handle over the gang (parity: worker_group.py:92)."""

    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK", slice_topology: str = "",
                 ready_timeout: float = 120.0):
        import ray_tpu as rt
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy)
        self.num_workers = num_workers
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        if slice_topology:
            # Slice-gang: bundle i -> rank-i host of ONE ICI slice, so the
            # jax.distributed process group matches TPU_WORKER_ID order.
            self.pg = placement_group(bundles, strategy="SLICE",
                                      slice_topology=slice_topology)
        else:
            self.pg = placement_group(bundles, strategy=placement_strategy)
        try:
            self.pg.ready(timeout=ready_timeout)
            cls = rt.remote(RayTrainWorker)
            self.workers = []
            for rank in range(num_workers):
                strategy = PlacementGroupSchedulingStrategy(
                    self.pg, placement_group_bundle_index=rank)
                w = cls.options(
                    num_cpus=resources_per_worker.get("CPU", 1.0),
                    num_tpus=resources_per_worker.get("TPU", 0.0),
                    resources={k: v for k, v in resources_per_worker.items()
                               if k not in ("CPU", "TPU")},
                    scheduling_strategy=strategy,
                ).remote(rank, num_workers, rank)
                self.workers.append(w)
        except BaseException:  # noqa: BLE001 - tear down the half-formed gang, then re-raise
            # half-formed gang: kill any actors already created AND release
            # the PG, so a retry plans against clean capacity (zombie ranks
            # would double-book the bundles the conductor just returned)
            from ray_tpu.util.placement_group import remove_placement_group
            for w in getattr(self, "workers", []):
                try:
                    rt.kill(w)
                except Exception:
                    pass
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            raise

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        import ray_tpu as rt
        return rt.get([w.execute.remote(fn, *args, **kwargs)
                       for w in self.workers], timeout=600)

    def broadcast_weights(self, params: Any,
                          apply_fn: Optional[Callable] = None) -> List[Any]:
        """Ship one weight payload to every rank via the collective-backed
        object plane (r16): ONE put + a broadcast tree pre-places the
        object on each distinct worker node, then every rank resolves it
        from its local store as a read-only array view. ``apply_fn(params)``
        runs on each rank with the resolved value (default: return it)."""
        import ray_tpu as rt
        from ray_tpu.train import weight_sync
        ref = weight_sync.broadcast_to_actors(params, self.workers)
        if apply_fn is None:
            futs = [w.execute.remote(_identity, ref) for w in self.workers]
        else:
            futs = [w.execute.remote(apply_fn, ref) for w in self.workers]
        return rt.get(futs, timeout=600)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        import ray_tpu as rt
        return rt.get(self.workers[rank].execute.remote(fn, *args, **kwargs),
                      timeout=600)

    def shutdown(self) -> None:
        import ray_tpu as rt
        from ray_tpu.util.placement_group import remove_placement_group
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
