"""ray_tpu.train — distributed training library (JAX-first).

Parity surface: reference python/ray/train (BaseTrainer base_trainer.py:554,
DataParallelTrainer data_parallel_trainer.py:56, BackendExecutor
backend_executor.py:43). The torch/NCCL backend is replaced by pjit-compiled
steps over a TPU mesh; `jax_step` is the single-controller compiled-step
factory, the Trainer/WorkerGroup layer orchestrates multi-host SPMD.
"""

from ray_tpu.train.jax_step import (
    TrainState,
    make_lm_train_step,
    make_resnet_train_step,
)

__all__ = ["TrainState", "make_lm_train_step", "make_resnet_train_step"]


def __getattr__(name):
    # Heavier trainer machinery is imported lazily so `import ray_tpu.train`
    # stays light for pure-step users.
    if name in ("ScalingConfig", "RunConfig", "CheckpointConfig",
                "FailureConfig", "Checkpoint", "JaxTrainer",
                "DataParallelTrainer", "report", "get_context"):
        try:
            from ray_tpu.train import trainer as _t
        except ModuleNotFoundError as e:
            raise AttributeError(name) from e
        return getattr(_t, name)
    raise AttributeError(name)
