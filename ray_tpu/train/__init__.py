"""ray_tpu.train — distributed training library (JAX-first).

Parity surface: reference python/ray/train (BaseTrainer base_trainer.py:554,
DataParallelTrainer data_parallel_trainer.py:56, BackendExecutor
backend_executor.py:43). The torch/NCCL backend is replaced by pjit-compiled
steps over a TPU mesh; `jax_step` is the single-controller compiled-step
factory, the Trainer/WorkerGroup layer orchestrates multi-host SPMD.
"""

from ray_tpu.train.jax_step import (
    TrainState,
    make_lm_train_step,
    make_resnet_train_step,
    make_vit_train_step,
)

_LAZY = {
    "ScalingConfig": ("ray_tpu.air.config", "ScalingConfig"),
    "RunConfig": ("ray_tpu.air.config", "RunConfig"),
    "CheckpointConfig": ("ray_tpu.air.config", "CheckpointConfig"),
    "FailureConfig": ("ray_tpu.air.config", "FailureConfig"),
    "Checkpoint": ("ray_tpu.air.checkpoint", "Checkpoint"),
    "Result": ("ray_tpu.air.result", "Result"),
    "session": ("ray_tpu.air", "session"),
    "report": ("ray_tpu.air.session", "report"),
    "JaxTrainer": ("ray_tpu.train.trainer", "JaxTrainer"),
    "TorchTrainer": ("ray_tpu.train.trainer", "TorchTrainer"),
    "TorchBackend": ("ray_tpu.train.backend_executor", "TorchBackend"),
    "torch_utils": ("ray_tpu.train.torch_utils", None),
    "DataParallelTrainer": ("ray_tpu.train.trainer", "DataParallelTrainer"),
    "BaseTrainer": ("ray_tpu.train.trainer", "BaseTrainer"),
    "BackendExecutor": ("ray_tpu.train.backend_executor", "BackendExecutor"),
    "JaxBackend": ("ray_tpu.train.backend_executor", "JaxBackend"),
    "WorkerGroup": ("ray_tpu.train.worker_group", "WorkerGroup"),
    "PipelineTrainer": ("ray_tpu.train.pipeline", "PipelineTrainer"),
    "CompiledPipeline": ("ray_tpu.train.pipeline", "CompiledPipeline"),
    "PipelineStageActor": ("ray_tpu.train.pipeline", "PipelineStageActor"),
}

__all__ = ["TrainState", "make_lm_train_step", "make_resnet_train_step",
           "make_vit_train_step",
           *_LAZY]


def __getattr__(name):
    # Heavier trainer machinery is imported lazily so `import ray_tpu.train`
    # stays light for pure-step users.
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(name)
    import importlib
    mod = importlib.import_module(entry[0])
    return mod if entry[1] is None else getattr(mod, entry[1])
