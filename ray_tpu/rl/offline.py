"""Offline RL IO: experience writers/readers + behavior cloning.

Role parity: rllib/offline/json_writer.py (JsonWriter — SampleBatches to
newline-delimited JSON shards), rllib/offline/json_reader.py (JsonReader —
shards back to SampleBatches, shuffled sampling), and the BC algorithm
(rllib/algorithms/bc) as the first offline-learning consumer: maximize
log-prob of the dataset actions on the shared RLModule policy tower.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.sample_batch import SampleBatch


class JsonWriter:
    """Write SampleBatches as newline-delimited JSON shard files."""

    def __init__(self, path: str, max_rows_per_file: int = 5000):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_rows = max_rows_per_file
        self._shard = 0
        self._rows_in_shard = 0
        self._fh = None

    def _file(self):
        if self._fh is None or self._rows_in_shard >= self.max_rows:
            if self._fh is not None:
                self._fh.close()
            self._fh = open(os.path.join(
                self.path, f"experiences-{self._shard:05d}.json"), "w")
            self._shard += 1
            self._rows_in_shard = 0
        return self._fh

    def write(self, batch: SampleBatch) -> None:
        cols = {k: np.asarray(v) for k, v in batch.items()}
        n = batch.count
        for i in range(n):
            row = {k: cols[k][i].tolist() for k in cols}
            f = self._file()   # rotates shards at max_rows_per_file
            f.write(json.dumps(row) + "\n")
            self._rows_in_shard += 1
        if self._fh is not None:
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class JsonReader:
    """Read experience shards back as SampleBatches."""

    def __init__(self, path: str, shuffle: bool = True, seed: int = 0):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(
                os.path.join(path, "*.json")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"no experience files under {path!r}")
        # Columnar in-memory layout: one numpy array per field (row dicts
        # cost ~10x in object overhead and a re-conversion per sample()).
        rows: List[dict] = []
        for fp in self.files:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        if not rows:
            raise ValueError(f"experience files under {path!r} are empty")
        self._cols: Dict[str, np.ndarray] = {
            k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        self._n = len(rows)
        self._rng = np.random.default_rng(seed)
        self._shuffle = shuffle

    def __len__(self) -> int:
        return self._n

    def _take(self, idx) -> SampleBatch:
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})

    def read_all(self) -> SampleBatch:
        return SampleBatch(dict(self._cols))

    def sample(self, num_rows: int) -> SampleBatch:
        idx = self._rng.integers(0, self._n, num_rows) \
            if self._shuffle else np.arange(num_rows) % self._n
        return self._take(idx)

    def iter_batches(self, batch_size: int = 256) -> Iterator[SampleBatch]:
        order = self._rng.permutation(self._n) if self._shuffle \
            else np.arange(self._n)
        for start in range(0, self._n, batch_size):
            yield self._take(order[start:start + batch_size])


def collect_experiences(env: Any, path: str, num_steps: int = 2000,
                        num_envs: int = 8, seed: int = 0,
                        policy_fn=None) -> str:
    """Roll a (random or given) policy and persist the transitions — the
    dataset-generation half of the offline workflow (parity: `rllib train
    ... --output`)."""
    from ray_tpu.rl.env import make_env
    venv = make_env(env, num_envs=num_envs, seed=seed)
    if policy_fn is None and venv.num_actions <= 0:
        raise NotImplementedError(
            "random-policy collection covers discrete action spaces; pass "
            "policy_fn for continuous envs")
    rng = np.random.default_rng(seed)
    writer = JsonWriter(path)
    obs = venv.vector_reset(seed=seed)
    rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
                            sb.DONES)}
    steps = 0
    while steps < num_steps:
        if policy_fn is None:
            actions = rng.integers(0, venv.num_actions, venv.num_envs)
        else:
            actions = np.asarray(policy_fn(obs))
        nxt, rew, done, _ = venv.vector_step(actions)
        rows[sb.OBS].append(obs.copy())
        rows[sb.ACTIONS].append(actions)
        rows[sb.REWARDS].append(rew)
        rows[sb.NEXT_OBS].append(nxt.copy())
        rows[sb.DONES].append(done)
        obs = nxt
        steps += venv.num_envs
    writer.write(SampleBatch({
        k: np.concatenate(v) if np.asarray(v[0]).ndim > 1
        else np.concatenate([np.asarray(x).reshape(-1) for x in v])
        for k, v in rows.items()}))
    writer.close()
    return path


class BCConfig:
    """Behavior-cloning config (parity: rllib/algorithms/bc/bc.py)."""

    def __init__(self):
        self.env = "CartPole-v1"     # for eval only
        self.input_path = ""
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iter = 50
        self.model_hiddens = (64, 64)
        self.seed = 0
        self.algo_class = BC

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self

    def training(self, **kw) -> "BCConfig":
        for k, v in kw.items():
            setattr(self, k, v)
        return self

    def build(self):
        return self.algo_class(self)


class BC:
    """Supervised policy learning from offline experiences: maximize
    log pi(a_t | s_t) over the dataset on the shared RLModule."""

    def __init__(self, config: BCConfig):
        import jax
        import optax

        from ray_tpu.rl.env import make_env
        from ray_tpu.rl.module import RLModule

        self.config = config
        self.reader = JsonReader(config.input_path, seed=config.seed)
        probe = make_env(config.env, num_envs=1, seed=config.seed)
        self.module = RLModule(
            obs_dim=probe.observation_dim, num_actions=probe.num_actions,
            hiddens=tuple(config.model_hiddens))
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.iteration = 0
        module, tx = self.module, self.tx

        def loss_fn(params, batch):
            logp, entropy, _ = module.logp_entropy(
                params, batch[sb.OBS], batch[sb.ACTIONS])
            return -logp.mean(), {"bc_logp": logp.mean(),
                                  "entropy": entropy.mean()}

        def step(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._step = jax.jit(step)

    def train(self) -> Dict[str, Any]:
        stats: Dict[str, Any] = {}
        for _ in range(self.config.updates_per_iter):
            batch = self.reader.sample(self.config.train_batch_size)
            batch = SampleBatch({
                sb.OBS: np.asarray(batch[sb.OBS], np.float32),
                sb.ACTIONS: np.asarray(batch[sb.ACTIONS])})
            self.params, self.opt_state, stats = self._step(
                self.params, self.opt_state, dict(batch))
        self.iteration += 1
        return {k: float(v) for k, v in stats.items()} | {
            "training_iteration": self.iteration}

    # (MARWIL below reuses this BC eval verbatim via inheritance.)
    def evaluate(self, num_episodes: int = 10) -> Dict[str, float]:
        """Greedy rollout of the cloned policy on the live env."""
        import jax

        from ray_tpu.rl.env import make_env
        venv = make_env(self.config.env, num_envs=8,
                        seed=self.config.seed + 1)
        act = jax.jit(self.module.greedy_actions)
        obs = venv.vector_reset(seed=self.config.seed + 1)
        while len(venv.completed_returns) < num_episodes:
            actions = np.asarray(act(self.params, obs))
            obs, _, _, _ = venv.vector_step(actions)
        returns = venv.completed_returns[:num_episodes]
        return {"episode_reward_mean": float(np.mean(returns))}


class MARWILConfig(BCConfig):
    """MARWIL config (parity: rllib/algorithms/marwil/marwil.py)."""

    def __init__(self):
        super().__init__()
        self.beta = 1.0          # advantage temperature (0 => plain BC)
        self.vf_coeff = 1.0
        self.gamma = 0.99
        self.algo_class = MARWIL


class MARWIL(BC):
    """Monotonic advantage re-weighted imitation learning.

    Role parity: rllib/algorithms/marwil — BC where each transition's
    log-prob is weighted by exp(beta * A_norm); a value tower learns
    one-step TD targets from the offline transitions (the dataset is
    shuffled transitions, so the advantage is the one-step
    r + gamma*V(s') - V(s) rather than the trajectory Monte-Carlo form).
    beta=0 reduces exactly to BC. One jitted update per batch.
    """

    def __init__(self, config: "MARWILConfig"):
        import jax
        import jax.numpy as jnp
        import optax

        super().__init__(config)  # builds module/params/tx + BC step
        beta, vf_coeff, gamma = config.beta, config.vf_coeff, config.gamma
        module, tx = self.module, self.tx

        def loss_fn(params, batch):
            logp, entropy, value = module.logp_entropy(
                params, batch[sb.OBS], batch[sb.ACTIONS])
            v_next = module.apply(params, batch[sb.NEXT_OBS])[1]
            td_target = jax.lax.stop_gradient(
                batch[sb.REWARDS] + gamma * (1.0 - batch[sb.DONES]) * v_next)
            adv = jax.lax.stop_gradient(td_target - value)
            adv_norm = adv / (jnp.std(adv) + 1e-8)
            weights = jnp.exp(jnp.clip(beta * adv_norm, -10.0, 10.0))
            pi_loss = -(weights * logp).mean()
            vf_loss = ((value - td_target) ** 2).mean()
            total = pi_loss + vf_coeff * vf_loss
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "mean_weight": weights.mean(),
                           "entropy": entropy.mean()}

        def step(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._step = jax.jit(step)

    def train(self) -> Dict[str, Any]:
        stats: Dict[str, Any] = {}
        for _ in range(self.config.updates_per_iter):
            batch = self.reader.sample(self.config.train_batch_size)
            batch = SampleBatch({
                sb.OBS: np.asarray(batch[sb.OBS], np.float32),
                sb.ACTIONS: np.asarray(batch[sb.ACTIONS]),
                sb.REWARDS: np.asarray(batch[sb.REWARDS], np.float32),
                sb.NEXT_OBS: np.asarray(batch[sb.NEXT_OBS], np.float32),
                sb.DONES: np.asarray(batch[sb.DONES], np.float32)})
            self.params, self.opt_state, stats = self._step(
                self.params, self.opt_state, dict(batch))
        self.iteration += 1
        return {k: float(v) for k, v in stats.items()} | {
            "training_iteration": self.iteration}
