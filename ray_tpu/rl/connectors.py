"""Connectors: composable obs/action transform pipelines.

Role parity: rllib/connectors/ — small stateful transforms between env and
policy (agent/obs side) and between policy and env (action side), kept
OUTSIDE the model so they checkpoint/restore with the worker and stay
consistent between sampling and serving. TPU-first: transforms are
vectorized numpy on the host — the jitted policy forward stays pure.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform. ``__call__`` maps a batched array to a batched
    array; get_state/set_state make pipelines checkpointable."""

    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class FlattenObs(Connector):
    """[B, ...] -> [B, prod(...)] (connectors/agent/obs_preproc role)."""

    def __call__(self, x):
        x = np.asarray(x)
        return x.reshape(x.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, x):
        return np.clip(x, self.low, self.high)


class MeanStdObs(Connector):
    """Running mean/std normalization (Welford), the classic obs filter
    (parity: rllib's MeanStdFilter connector). Frozen via ``update=False``
    for evaluation."""

    def __init__(self, eps: float = 1e-8, update: bool = True):
        self.eps = eps
        self.update = update
        self._n = 0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, x):
        x = np.asarray(x, np.float64)
        if self.update and len(x):
            # batched Welford (Chan parallel merge): O(1) numpy calls per
            # batch, not per row
            bn = len(x)
            bmean = x.mean(axis=0)
            bm2 = ((x - bmean) ** 2).sum(axis=0)
            if self._mean is None:
                self._n, self._mean, self._m2 = bn, bmean, bm2
            else:
                delta = bmean - self._mean
                tot = self._n + bn
                self._mean = self._mean + delta * (bn / tot)
                self._m2 = self._m2 + bm2 + \
                    delta * delta * (self._n * bn / tot)
                self._n = tot
        if self._mean is None or self._n < 2:
            return x.astype(np.float32)
        std = np.sqrt(self._m2 / (self._n - 1)) + self.eps
        return ((x - self._mean) / std).astype(np.float32)

    def get_state(self) -> dict:
        # copies: a checkpointed state must not alias live (mutating) stats
        return {"n": self._n,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: dict) -> None:
        self._n = state["n"]
        self._mean = None if state["mean"] is None else \
            np.array(state["mean"], np.float64)
        self._m2 = None if state["m2"] is None else \
            np.array(state["m2"], np.float64)


class ClipActions(Connector):
    """Bound continuous actions to the env's action range
    (connectors/action/clip role)."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, a):
        return np.clip(a, self.low, self.high)


class UnsquashActions(Connector):
    """Map tanh-squashed [-1, 1] policy outputs to [low, high]."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, a):
        a = np.tanh(np.asarray(a, np.float64))
        return (self.low + (a + 1.0) * 0.5 *
                (self.high - self.low)).astype(np.float32)


class ConnectorPipeline(Connector):
    """Ordered composition with aggregate state."""

    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state or str(i) in state:
                c.set_state(state.get(i, state.get(str(i))))

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self
