"""V-trace: off-policy corrected value targets (IMPALA/APPO).

Role parity: rllib/algorithms/impala/vtrace.py (the reference's TF/torch
v-trace ops). TPU-first: one lax.scan over the time axis on [T, N] arrays —
no python loops, jit/grad-safe, batched over N envs.

Math (Espeholt et al. 2018):
    delta_t = rho_t (r_t + gamma_t V(x_{t+1}) - V(x_t))
    vs_t    = V(x_t) + delta_t + gamma_t c_t (vs_{t+1} - V(x_{t+1}))
    adv_t   = rho_t (r_t + gamma_t vs_{t+1} - V(x_t))
with rho_t = min(rho_bar, pi/mu), c_t = min(c_bar, pi/mu), and gamma_t = 0
across episode boundaries (dones).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def vtrace_returns(behavior_logp, target_logp, rewards, values, dones,
                   bootstrap_value, *, gamma: float = 0.99,
                   rho_bar: float = 1.0, c_bar: float = 1.0):
    """All inputs [T, N] (bootstrap_value [N]) -> (vs [T, N], pg_adv [T, N]).

    ``dones[t]=1`` means the episode ended after step t: the next state's
    value does not flow back across the boundary.
    """
    log_rhos = target_logp - behavior_logp
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    clipped_cs = jnp.minimum(c_bar, rhos)
    discounts = gamma * (1.0 - dones)

    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    # Within-trajectory bootstrap: the value AFTER a terminal step is 0 via
    # the discount mask, so values_next needs no done handling itself.
    deltas = clipped_rhos * (rewards + discounts * values_next - values)

    def backward(carry, inp):
        delta, disc, c, v_next_minus = inp
        # carry = vs_{t+1} - V(x_{t+1})
        acc = delta + disc * c * carry
        return acc, acc

    _, acc = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, clipped_cs, values_next), reverse=True)
    vs = values + acc

    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def vtrace_reference(behavior_logp, target_logp, rewards, values, dones,
                     bootstrap_value, *, gamma=0.99, rho_bar=1.0,
                     c_bar=1.0):
    """Slow numpy double-loop implementation of the same recurrences, for
    tests only (the pattern the kernels in ops/ use for verification)."""
    import numpy as np
    T, N = rewards.shape
    rhos = np.minimum(rho_bar, np.exp(target_logp - behavior_logp))
    cs = np.minimum(c_bar, np.exp(target_logp - behavior_logp))
    disc = gamma * (1.0 - dones)
    v_next = np.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = rhos * (rewards + disc * v_next - values)
    vs = np.zeros((T, N))
    acc = np.zeros(N)
    for t in reversed(range(T)):
        acc = deltas[t] + disc[t] * cs[t] * acc
        vs[t] = values[t] + acc
    vs_next = np.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rhos * (rewards + disc * vs_next - values)
    return vs, pg_adv
