"""RLModule: the framework-agnostic policy/value network abstraction.

Role parity: rllib/core/rl_module/rl_module.py:215 — one object owning the
network definition with explicit inference/exploration/train forwards. Here
it is a pure-functional jax pair (init, apply): apply(params, obs) ->
(logits, value). Distributions are categorical (discrete) or diagonal
gaussian (continuous); both sampled with jax PRNG so rollout forwards are
one jitted batched call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(key, sizes: Sequence[int]) -> list:
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * \
            jnp.sqrt(2.0 / sizes[i])
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros(sizes[i + 1], jnp.float32)})
    return params


def mlp_apply(params: list, x, activate_last: bool = False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class RLModule:
    """Policy + value MLPs with shared-nothing towers."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), action_dim: int = 1):
        self.obs_dim = obs_dim
        self.num_actions = num_actions   # -1 => continuous gaussian
        self.action_dim = action_dim     # continuous dims (k); discrete: n/a
        self.hiddens = tuple(hiddens)
        self.out_dim = num_actions if num_actions > 0 else 2 * action_dim

    def init(self, key) -> Dict[str, Any]:
        kp, kv = jax.random.split(key)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, self.out_dim)),
            "vf": mlp_init(kv, (self.obs_dim, *self.hiddens, 1)),
        }

    def apply(self, params, obs):
        """-> (logits [B, A], value [B])."""
        logits = mlp_apply(params["pi"], obs)
        value = mlp_apply(params["vf"], obs)[..., 0]
        return logits, value

    def _mean_logstd(self, logits):
        """Continuous head: [B, 2k] -> mean [B, k], log_std [B, k] (k=1
        squeezes to [B] to keep 1-D env arrays unchanged)."""
        k = self.action_dim
        mean, log_std = logits[..., :k], logits[..., k:]
        if k == 1:
            mean, log_std = mean[..., 0], log_std[..., 0]
        return mean, log_std

    # -- distribution ops (categorical / diagonal gaussian) ---------------
    def sample_actions(self, params, obs, key):
        """-> (actions, logp, value) — one jitted batched call."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            actions = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), actions]
        else:
            mean, log_std = self._mean_logstd(logits)
            eps = jax.random.normal(key, mean.shape)
            actions = mean + jnp.exp(log_std) * eps
            logp = -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            if logp.ndim > 1:
                logp = logp.sum(axis=-1)   # diagonal: sum per-dim logps
        return actions, logp, value

    def logp_entropy(self, params, obs, actions):
        """-> (logp, entropy, value) for train-time evaluation."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            logp_all = jax.nn.log_softmax(logits)
            logp = logp_all[jnp.arange(logits.shape[0]),
                            actions.astype(jnp.int32)]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        else:
            mean, log_std = self._mean_logstd(logits)
            z = (actions - mean) / jnp.exp(log_std)
            logp = -0.5 * (z ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            entropy = log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)
            if logp.ndim > 1:
                logp = logp.sum(axis=-1)
                entropy = entropy.sum(axis=-1)
        return logp, entropy, value

    def greedy_actions(self, params, obs):
        logits, _ = self.apply(params, obs)
        if self.num_actions > 0:
            return jnp.argmax(logits, axis=-1)
        return self._mean_logstd(logits)[0]


class ConvRLModule(RLModule):
    """CNN-encoded policy/value (parity: rllib/models vision nets +
    catalog conv_filters). Obs arrive FLATTENED [B, H*W*C] (the vectorized
    env convention); the module reshapes internally, so collectors and
    learners are unchanged.

    filters: sequence of (out_channels, kernel, stride)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 obs_shape: Sequence[int],
                 filters: Sequence[Sequence[int]] = ((16, 3, 2), (32, 3, 2)),
                 hiddens: Sequence[int] = (128,), action_dim: int = 1):
        if int(np.prod(obs_shape)) != obs_dim:
            raise ValueError(f"obs_shape {obs_shape} != obs_dim {obs_dim}")
        super().__init__(obs_dim, num_actions, hiddens, action_dim)
        self.obs_shape = tuple(obs_shape)          # (H, W, C)
        self.filters = tuple(tuple(f) for f in filters)

    def _conv_out_dim(self) -> int:
        h, w, _ = self.obs_shape
        c = self.obs_shape[-1]
        for (c_out, k, s) in self.filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            c = c_out
        return h * w * c

    def init(self, key) -> Dict[str, Any]:
        kc, kp, kv = jax.random.split(key, 3)
        conv = []
        c_in = self.obs_shape[-1]
        for (c_out, k, s) in self.filters:
            kc, sub = jax.random.split(kc)
            fan_in = k * k * c_in
            conv.append({
                "w": (jax.random.normal(sub, (k, k, c_in, c_out))
                      * jnp.sqrt(2.0 / fan_in)).astype(jnp.float32),
                "b": jnp.zeros(c_out, jnp.float32),
            })
            c_in = c_out
        feat = self._conv_out_dim()
        return {
            "conv": conv,
            "pi": mlp_init(kp, (feat, *self.hiddens, self.out_dim)),
            "vf": mlp_init(kv, (feat, *self.hiddens, 1)),
        }

    def _encode(self, params, obs):
        x = obs.reshape((obs.shape[0],) + self.obs_shape)
        for layer, (_, _, s) in zip(params["conv"], self.filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
            x = jax.nn.relu(x)
        return x.reshape(x.shape[0], -1)

    def apply(self, params, obs):
        feat = self._encode(params, obs)
        logits = mlp_apply(params["pi"], feat)
        value = mlp_apply(params["vf"], feat)[..., 0]
        return logits, value


def lstm_init(key, in_dim: int, hidden: int) -> Dict[str, Any]:
    k1, k2 = jax.random.split(key)
    scale = jnp.sqrt(1.0 / hidden)
    return {
        "wx": (jax.random.normal(k1, (in_dim, 4 * hidden)) * scale
               ).astype(jnp.float32),
        "wh": (jax.random.normal(k2, (hidden, 4 * hidden)) * scale
               ).astype(jnp.float32),
        "b": jnp.zeros(4 * hidden, jnp.float32),
    }


def lstm_step(params, carry, x):
    """One LSTM cell step: carry=(h, c), x [B, D]."""
    h, c = carry
    gates = x @ params["wx"] + h @ params["wh"] + params["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return (h, c), h


class RecurrentRLModule:
    """LSTM policy/value (parity: rllib/models recurrent nets / use_lstm).
    Sequence-first API: apply_seq consumes [T, B, D] with an explicit
    carried state, scanning the cell with lax.scan (TPU-friendly: one
    compiled program per sequence length, no python-loop unrolling).
    ``dones`` resets the carry mid-sequence so crossing episode boundaries
    inside a rollout window is safe."""

    def __init__(self, obs_dim: int, num_actions: int, hidden_size: int = 64,
                 action_dim: int = 1):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.action_dim = action_dim
        self.hidden_size = hidden_size
        self.out_dim = num_actions if num_actions > 0 else 2 * action_dim

    def init(self, key) -> Dict[str, Any]:
        kl, kp, kv = jax.random.split(key, 3)
        return {
            "lstm": lstm_init(kl, self.obs_dim, self.hidden_size),
            "pi": mlp_init(kp, (self.hidden_size, self.out_dim)),
            "vf": mlp_init(kv, (self.hidden_size, 1)),
        }

    def initial_state(self, batch: int):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z, z)

    def apply_seq(self, params, obs_seq, state, dones_seq=None):
        """obs_seq [T, B, D], state (h, c) -> (logits [T, B, A],
        values [T, B], final_state).

        dones follows the rollout convention: dones[t]=1 means the episode
        ended AFTER obs[t] (obs[t+1] is the reset obs) — so the carry is
        zeroed before processing step t+1, never before step t itself."""
        def step(carry, inp):
            if dones_seq is None:
                x, = inp
            else:
                x, d_prev = inp
                mask = (1.0 - d_prev)[:, None]
                carry = (carry[0] * mask, carry[1] * mask)
            carry, h = lstm_step(params["lstm"], carry, x)
            return carry, h
        if dones_seq is None:
            xs = (obs_seq,)
        else:
            prev = jnp.concatenate(
                [jnp.zeros_like(dones_seq[:1]), dones_seq[:-1]], axis=0)
            xs = (obs_seq, prev)
        state, hs = jax.lax.scan(step, state, xs)
        logits = mlp_apply(params["pi"], hs)
        values = mlp_apply(params["vf"], hs)[..., 0]
        return logits, values, state


def make_module(spec: Dict[str, Any]):
    """Module factory from a module_spec dict (parity: rllib catalog /
    RLModuleSpec.build). encoder: "mlp" (default) | "cnn" | "lstm"."""
    spec = dict(spec)
    encoder = spec.pop("encoder", "mlp")
    if encoder == "mlp":
        return RLModule(**spec)
    if encoder == "cnn":
        return ConvRLModule(**spec)
    if encoder == "lstm":
        spec.pop("hiddens", None)
        return RecurrentRLModule(**spec)
    if encoder in ("gtrxl", "attention"):
        spec.pop("hiddens", None)
        return AttentionRLModule(**spec)
    raise ValueError(f"unknown encoder {encoder!r}")


# ---------------------------------------------------------------------------
# GTrXL: gated transformer-XL encoder (attention catalog entry)
# ---------------------------------------------------------------------------

def _gru_gate_init(key, dim: int) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    scale = jnp.sqrt(1.0 / dim)
    def lin(k):
        return (jax.random.normal(k, (2 * dim, dim)) * scale
                ).astype(jnp.float32)
    return {"wr": lin(k1), "wz": lin(k2), "wh": lin(k3),
            # bg > 0 biases the update gate toward IDENTITY at init — the
            # key trick of the GTrXL paper (arXiv:1910.06764 eq. 6): the
            # block starts as a skip connection, which is what makes
            # transformers trainable under an RL objective.
            "bg": jnp.full((dim,), 2.0, jnp.float32)}


def _gru_gate(params, x, y):
    """GRU-style gating g(x, y): x = stream (skip), y = block output."""
    xy = jnp.concatenate([x, y], axis=-1)
    r = jax.nn.sigmoid(xy @ params["wr"])
    z = jax.nn.sigmoid(xy @ params["wz"] - params["bg"])
    h = jnp.tanh(jnp.concatenate([r * x, y], axis=-1) @ params["wh"])
    return (1.0 - z) * x + z * h


class AttentionRLModule:
    """GTrXL-style policy/value net (parity: rllib attention_net.py
    GTrXLNet, catalog use_attention): L transformer blocks with
    layer-norm-first attention over a sliding window of past hidden
    states (the TrXL memory), each sublayer merged into the residual
    stream through a GRU gate biased to identity.

    Sequence-first like RecurrentRLModule: apply_seq consumes [T, B, D]
    plus a memory state [L, B, M, H] and returns (logits, values, new
    memory). lax.scan over time keeps one compiled program per sequence
    length; attention at step t sees the M most recent cached states."""

    def __init__(self, obs_dim: int, num_actions: int, *,
                 hidden_size: int = 64, num_layers: int = 2,
                 num_heads: int = 4, memory_len: int = 16,
                 action_dim: int = 1):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.action_dim = action_dim
        self.h = hidden_size
        self.layers = num_layers
        self.heads = num_heads
        self.mem = memory_len
        self.out_dim = num_actions if num_actions > 0 else 2 * action_dim

    def init(self, key) -> Dict[str, Any]:
        ks = jax.random.split(key, 3 + self.layers)
        params: Dict[str, Any] = {
            "embed": mlp_init(ks[0], (self.obs_dim, self.h)),
            "pi": mlp_init(ks[1], (self.h, self.out_dim)),
            "vf": mlp_init(ks[2], (self.h, 1)),
            "blocks": [],
        }
        scale = jnp.sqrt(1.0 / self.h)
        for li in range(self.layers):
            kq, kk, kv, ko, kf1, kf2, kg1, kg2 = jax.random.split(
                ks[3 + li], 8)
            def lin(k, dout):
                return (jax.random.normal(k, (self.h, dout)) * scale
                        ).astype(jnp.float32)
            params["blocks"].append({
                "wq": lin(kq, self.h), "wk": lin(kk, self.h),
                "wv": lin(kv, self.h), "wo": lin(ko, self.h),
                "ff1": mlp_init(kf1, (self.h, 4 * self.h)),
                "ff2": mlp_init(kf2, (4 * self.h, self.h)),
                "gate_attn": _gru_gate_init(kg1, self.h),
                "gate_ff": _gru_gate_init(kg2, self.h),
            })
        return params

    def initial_state(self, batch: int):
        return jnp.zeros((self.layers, batch, self.mem, self.h),
                         jnp.float32)

    @staticmethod
    def _norm(x):
        mu = x.mean(-1, keepdims=True)
        sd = jnp.sqrt(((x - mu) ** 2).mean(-1, keepdims=True) + 1e-5)
        return (x - mu) / sd

    def _block(self, bp, x, mem_l):
        """x [B, H]; mem_l [B, M, H] (oldest..newest) -> (out, new_mem)."""
        B = x.shape[0]
        hd = self.h // self.heads
        ctx = jnp.concatenate([mem_l, x[:, None, :]], axis=1)  # [B,M+1,H]
        xin = self._norm(x)
        cin = self._norm(ctx)
        q = (xin @ bp["wq"]).reshape(B, self.heads, hd)
        k = (cin @ bp["wk"]).reshape(B, -1, self.heads, hd)
        v = (cin @ bp["wv"]).reshape(B, -1, self.heads, hd)
        att = jnp.einsum("bhd,bmhd->bhm", q, k) / jnp.sqrt(hd)
        att = jax.nn.softmax(att, axis=-1)
        y = jnp.einsum("bhm,bmhd->bhd", att, v).reshape(B, self.h)
        y = jax.nn.relu(y @ bp["wo"])
        x = _gru_gate(bp["gate_attn"], x, y)
        f = mlp_apply(bp["ff2"], mlp_apply(bp["ff1"], self._norm(x),
                                           activate_last=True))
        x = _gru_gate(bp["gate_ff"], x, f)
        new_mem = jnp.concatenate([mem_l[:, 1:], x[:, None, :]], axis=1)
        return x, new_mem

    def apply_seq(self, params, obs_seq, state, dones_seq=None):
        """obs_seq [T, B, D], state [L, B, M, H] -> (logits [T, B, A],
        values [T, B], final_state). dones zero the memory AFTER a
        terminal step (same convention as RecurrentRLModule)."""
        def step(mem, inp):
            if dones_seq is None:
                (x,) = inp
            else:
                x, d_prev = inp
                mem = mem * (1.0 - d_prev)[None, :, None, None]
            h = mlp_apply(params["embed"], x, activate_last=True)
            new_mem = []
            for li in range(self.layers):
                h, m = self._block(params["blocks"][li], h, mem[li])
                new_mem.append(m)
            return jnp.stack(new_mem), h
        if dones_seq is None:
            xs = (obs_seq,)
        else:
            prev = jnp.concatenate(
                [jnp.zeros_like(dones_seq[:1]), dones_seq[:-1]], axis=0)
            xs = (obs_seq, prev)
        state, hs = jax.lax.scan(step, state, xs)
        logits = mlp_apply(params["pi"], hs)
        values = mlp_apply(params["vf"], hs)[..., 0]
        return logits, values, state
