"""RLModule: the framework-agnostic policy/value network abstraction.

Role parity: rllib/core/rl_module/rl_module.py:215 — one object owning the
network definition with explicit inference/exploration/train forwards. Here
it is a pure-functional jax pair (init, apply): apply(params, obs) ->
(logits, value). Distributions are categorical (discrete) or diagonal
gaussian (continuous); both sampled with jax PRNG so rollout forwards are
one jitted batched call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(key, sizes: Sequence[int]) -> list:
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * \
            jnp.sqrt(2.0 / sizes[i])
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros(sizes[i + 1], jnp.float32)})
    return params


def mlp_apply(params: list, x, activate_last: bool = False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class RLModule:
    """Policy + value MLPs with shared-nothing towers."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64), action_dim: int = 1):
        self.obs_dim = obs_dim
        self.num_actions = num_actions   # -1 => continuous gaussian
        self.action_dim = action_dim     # continuous dims (k); discrete: n/a
        self.hiddens = tuple(hiddens)
        self.out_dim = num_actions if num_actions > 0 else 2 * action_dim

    def init(self, key) -> Dict[str, Any]:
        kp, kv = jax.random.split(key)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, self.out_dim)),
            "vf": mlp_init(kv, (self.obs_dim, *self.hiddens, 1)),
        }

    def apply(self, params, obs):
        """-> (logits [B, A], value [B])."""
        logits = mlp_apply(params["pi"], obs)
        value = mlp_apply(params["vf"], obs)[..., 0]
        return logits, value

    def _mean_logstd(self, logits):
        """Continuous head: [B, 2k] -> mean [B, k], log_std [B, k] (k=1
        squeezes to [B] to keep 1-D env arrays unchanged)."""
        k = self.action_dim
        mean, log_std = logits[..., :k], logits[..., k:]
        if k == 1:
            mean, log_std = mean[..., 0], log_std[..., 0]
        return mean, log_std

    # -- distribution ops (categorical / diagonal gaussian) ---------------
    def sample_actions(self, params, obs, key):
        """-> (actions, logp, value) — one jitted batched call."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            actions = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), actions]
        else:
            mean, log_std = self._mean_logstd(logits)
            eps = jax.random.normal(key, mean.shape)
            actions = mean + jnp.exp(log_std) * eps
            logp = -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            if logp.ndim > 1:
                logp = logp.sum(axis=-1)   # diagonal: sum per-dim logps
        return actions, logp, value

    def logp_entropy(self, params, obs, actions):
        """-> (logp, entropy, value) for train-time evaluation."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            logp_all = jax.nn.log_softmax(logits)
            logp = logp_all[jnp.arange(logits.shape[0]),
                            actions.astype(jnp.int32)]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        else:
            mean, log_std = self._mean_logstd(logits)
            z = (actions - mean) / jnp.exp(log_std)
            logp = -0.5 * (z ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            entropy = log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)
            if logp.ndim > 1:
                logp = logp.sum(axis=-1)
                entropy = entropy.sum(axis=-1)
        return logp, entropy, value

    def greedy_actions(self, params, obs):
        logits, _ = self.apply(params, obs)
        if self.num_actions > 0:
            return jnp.argmax(logits, axis=-1)
        return self._mean_logstd(logits)[0]


class ConvRLModule(RLModule):
    """CNN-encoded policy/value (parity: rllib/models vision nets +
    catalog conv_filters). Obs arrive FLATTENED [B, H*W*C] (the vectorized
    env convention); the module reshapes internally, so collectors and
    learners are unchanged.

    filters: sequence of (out_channels, kernel, stride)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 obs_shape: Sequence[int],
                 filters: Sequence[Sequence[int]] = ((16, 3, 2), (32, 3, 2)),
                 hiddens: Sequence[int] = (128,), action_dim: int = 1):
        if int(np.prod(obs_shape)) != obs_dim:
            raise ValueError(f"obs_shape {obs_shape} != obs_dim {obs_dim}")
        super().__init__(obs_dim, num_actions, hiddens, action_dim)
        self.obs_shape = tuple(obs_shape)          # (H, W, C)
        self.filters = tuple(tuple(f) for f in filters)

    def _conv_out_dim(self) -> int:
        h, w, _ = self.obs_shape
        c = self.obs_shape[-1]
        for (c_out, k, s) in self.filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            c = c_out
        return h * w * c

    def init(self, key) -> Dict[str, Any]:
        kc, kp, kv = jax.random.split(key, 3)
        conv = []
        c_in = self.obs_shape[-1]
        for (c_out, k, s) in self.filters:
            kc, sub = jax.random.split(kc)
            fan_in = k * k * c_in
            conv.append({
                "w": (jax.random.normal(sub, (k, k, c_in, c_out))
                      * jnp.sqrt(2.0 / fan_in)).astype(jnp.float32),
                "b": jnp.zeros(c_out, jnp.float32),
            })
            c_in = c_out
        feat = self._conv_out_dim()
        return {
            "conv": conv,
            "pi": mlp_init(kp, (feat, *self.hiddens, self.out_dim)),
            "vf": mlp_init(kv, (feat, *self.hiddens, 1)),
        }

    def _encode(self, params, obs):
        x = obs.reshape((obs.shape[0],) + self.obs_shape)
        for layer, (_, _, s) in zip(params["conv"], self.filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
            x = jax.nn.relu(x)
        return x.reshape(x.shape[0], -1)

    def apply(self, params, obs):
        feat = self._encode(params, obs)
        logits = mlp_apply(params["pi"], feat)
        value = mlp_apply(params["vf"], feat)[..., 0]
        return logits, value


def lstm_init(key, in_dim: int, hidden: int) -> Dict[str, Any]:
    k1, k2 = jax.random.split(key)
    scale = jnp.sqrt(1.0 / hidden)
    return {
        "wx": (jax.random.normal(k1, (in_dim, 4 * hidden)) * scale
               ).astype(jnp.float32),
        "wh": (jax.random.normal(k2, (hidden, 4 * hidden)) * scale
               ).astype(jnp.float32),
        "b": jnp.zeros(4 * hidden, jnp.float32),
    }


def lstm_step(params, carry, x):
    """One LSTM cell step: carry=(h, c), x [B, D]."""
    h, c = carry
    gates = x @ params["wx"] + h @ params["wh"] + params["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return (h, c), h


class RecurrentRLModule:
    """LSTM policy/value (parity: rllib/models recurrent nets / use_lstm).
    Sequence-first API: apply_seq consumes [T, B, D] with an explicit
    carried state, scanning the cell with lax.scan (TPU-friendly: one
    compiled program per sequence length, no python-loop unrolling).
    ``dones`` resets the carry mid-sequence so crossing episode boundaries
    inside a rollout window is safe."""

    def __init__(self, obs_dim: int, num_actions: int, hidden_size: int = 64,
                 action_dim: int = 1):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.action_dim = action_dim
        self.hidden_size = hidden_size
        self.out_dim = num_actions if num_actions > 0 else 2 * action_dim

    def init(self, key) -> Dict[str, Any]:
        kl, kp, kv = jax.random.split(key, 3)
        return {
            "lstm": lstm_init(kl, self.obs_dim, self.hidden_size),
            "pi": mlp_init(kp, (self.hidden_size, self.out_dim)),
            "vf": mlp_init(kv, (self.hidden_size, 1)),
        }

    def initial_state(self, batch: int):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z, z)

    def apply_seq(self, params, obs_seq, state, dones_seq=None):
        """obs_seq [T, B, D], state (h, c) -> (logits [T, B, A],
        values [T, B], final_state).

        dones follows the rollout convention: dones[t]=1 means the episode
        ended AFTER obs[t] (obs[t+1] is the reset obs) — so the carry is
        zeroed before processing step t+1, never before step t itself."""
        def step(carry, inp):
            if dones_seq is None:
                x, = inp
            else:
                x, d_prev = inp
                mask = (1.0 - d_prev)[:, None]
                carry = (carry[0] * mask, carry[1] * mask)
            carry, h = lstm_step(params["lstm"], carry, x)
            return carry, h
        if dones_seq is None:
            xs = (obs_seq,)
        else:
            prev = jnp.concatenate(
                [jnp.zeros_like(dones_seq[:1]), dones_seq[:-1]], axis=0)
            xs = (obs_seq, prev)
        state, hs = jax.lax.scan(step, state, xs)
        logits = mlp_apply(params["pi"], hs)
        values = mlp_apply(params["vf"], hs)[..., 0]
        return logits, values, state


def make_module(spec: Dict[str, Any]):
    """Module factory from a module_spec dict (parity: rllib catalog /
    RLModuleSpec.build). encoder: "mlp" (default) | "cnn" | "lstm"."""
    spec = dict(spec)
    encoder = spec.pop("encoder", "mlp")
    if encoder == "mlp":
        return RLModule(**spec)
    if encoder == "cnn":
        return ConvRLModule(**spec)
    if encoder == "lstm":
        spec.pop("hiddens", None)
        return RecurrentRLModule(**spec)
    raise ValueError(f"unknown encoder {encoder!r}")
