"""RLModule: the framework-agnostic policy/value network abstraction.

Role parity: rllib/core/rl_module/rl_module.py:215 — one object owning the
network definition with explicit inference/exploration/train forwards. Here
it is a pure-functional jax pair (init, apply): apply(params, obs) ->
(logits, value). Distributions are categorical (discrete) or diagonal
gaussian (continuous); both sampled with jax PRNG so rollout forwards are
one jitted batched call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(key, sizes: Sequence[int]) -> list:
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * \
            jnp.sqrt(2.0 / sizes[i])
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros(sizes[i + 1], jnp.float32)})
    return params


def mlp_apply(params: list, x, activate_last: bool = False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class RLModule:
    """Policy + value MLPs with shared-nothing towers."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions   # -1 => continuous 1-D gaussian
        self.hiddens = tuple(hiddens)
        self.out_dim = num_actions if num_actions > 0 else 2

    def init(self, key) -> Dict[str, Any]:
        kp, kv = jax.random.split(key)
        return {
            "pi": mlp_init(kp, (self.obs_dim, *self.hiddens, self.out_dim)),
            "vf": mlp_init(kv, (self.obs_dim, *self.hiddens, 1)),
        }

    def apply(self, params, obs):
        """-> (logits [B, A], value [B])."""
        logits = mlp_apply(params["pi"], obs)
        value = mlp_apply(params["vf"], obs)[..., 0]
        return logits, value

    # -- distribution ops (categorical / gaussian) -----------------------
    def sample_actions(self, params, obs, key):
        """-> (actions, logp, value) — one jitted batched call."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            actions = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), actions]
        else:
            mean, log_std = logits[..., 0], logits[..., 1]
            eps = jax.random.normal(key, mean.shape)
            actions = mean + jnp.exp(log_std) * eps
            logp = -0.5 * (eps ** 2 + 2 * log_std +
                           jnp.log(2 * jnp.pi))
        return actions, logp, value

    def logp_entropy(self, params, obs, actions):
        """-> (logp, entropy, value) for train-time evaluation."""
        logits, value = self.apply(params, obs)
        if self.num_actions > 0:
            logp_all = jax.nn.log_softmax(logits)
            logp = logp_all[jnp.arange(logits.shape[0]),
                            actions.astype(jnp.int32)]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        else:
            mean, log_std = logits[..., 0], logits[..., 1]
            z = (actions - mean) / jnp.exp(log_std)
            logp = -0.5 * (z ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            entropy = log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)
        return logp, entropy, value

    def greedy_actions(self, params, obs):
        logits, _ = self.apply(params, obs)
        if self.num_actions > 0:
            return jnp.argmax(logits, axis=-1)
        return logits[..., 0]
