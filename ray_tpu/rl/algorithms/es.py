"""Evolution Strategies (parity: rllib/algorithms/es/es.py — the OpenAI-ES
scheme): gradient-free search over policy parameters. Each iteration
broadcasts the CURRENT weights once; workers regenerate their antithetic
perturbations from a SEED (the reference's shared-noise-table trick —
only seeds and fitness scalars cross the wire, never perturbed weight
copies), evaluate an episode each way, and the driver applies the
rank-weighted update theta += alpha/(n*sigma) * sum(F_i * eps_i).

TPU-first note: the policy forward is a jitted MLP; perturbation +
update arithmetic is flat-vector numpy on the driver — ES has no
backward pass, so the chip's only job is batched rollout forwards.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import episode_stats_of, make_env
from ray_tpu.rl.module import make_module


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_perturbations = 16      # antithetic pairs per iteration
        self.sigma = 0.1                 # perturbation scale
        self.lr = 0.05
        self.episode_horizon = 200
        self.weight_decay = 0.005
        self.algo_class = ES


def _flatten(params) -> np.ndarray:
    import jax
    leaves = jax.tree.leaves(params)
    return np.concatenate([np.asarray(x).ravel() for x in leaves])


def _unflatten(params_template, flat: np.ndarray):
    import jax
    leaves, treedef = jax.tree.flatten(params_template)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.asarray(leaf).size)
        out.append(flat[off:off + n].reshape(np.asarray(leaf).shape)
                   .astype(np.asarray(leaf).dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


class ESWorker:
    """Actor: evaluates seed-derived antithetic perturbations."""

    def __init__(self, env: Any, module_spec: dict, horizon: int,
                 sigma: float, seed: int = 0):
        import jax
        self.env = make_env(env, num_envs=1, seed=seed)
        self.module = make_module(module_spec)
        self.horizon = horizon
        self.sigma = sigma
        template = self.module.init(jax.random.PRNGKey(0))
        self._template = jax.device_get(template)
        self._dim = _flatten(self._template).size
        self._greedy = jax.jit(self.module.greedy_actions)

    def _episode_return(self, flat: np.ndarray) -> float:
        params = _unflatten(self._template, flat)
        obs = self.env.vector_reset(seed=None)
        total = 0.0
        for _ in range(self.horizon):
            a = np.asarray(self._greedy(params, obs))
            obs, rew, done, _ = self.env.vector_step(a)
            total += float(rew[0])
            if bool(done[0]):
                break
        return total

    def evaluate(self, flat_weights: np.ndarray,
                 seeds: List[int]) -> List[tuple]:
        """-> [(seed, F(theta+sigma*eps), F(theta-sigma*eps)), ...]."""
        out = []
        for s in seeds:
            eps = np.random.default_rng(s).standard_normal(
                self._dim).astype(np.float32)
            out.append((s,
                        self._episode_return(flat_weights + self.sigma * eps),
                        self._episode_return(flat_weights - self.sigma * eps)))
        return out

    def episode_stats(self) -> dict:
        return episode_stats_of(self.env)


class ES(Algorithm):
    def setup(self) -> None:
        import jax
        import ray_tpu as rt
        cfg: ESConfig = self.config  # type: ignore[assignment]
        self.module = make_module(self.module_spec)
        params = jax.device_get(self.module.init(
            jax.random.PRNGKey(cfg.seed)))
        self._template = params
        self.theta = _flatten(params)
        self._rng = np.random.default_rng(cfg.seed)
        worker_cls = rt.remote(ESWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                cfg.env, self.module_spec, cfg.episode_horizon, cfg.sigma,
                seed=cfg.seed + i + 1)
            for i in range(max(1, cfg.num_rollout_workers))]

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu as rt
        cfg: ESConfig = self.config  # type: ignore[assignment]
        n = cfg.num_perturbations
        seeds = [int(s) for s in self._rng.integers(0, 1 << 31, n)]
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(self.theta, [int(x) for x in chunk])
                for w, chunk in zip(self.workers, chunks) if len(chunk)]
        results = [r for rs in rt.get(futs, timeout=600) for r in rs]
        # rank transform (centered): robust to reward scale
        pos = np.asarray([fp for _, fp, _ in results])
        neg = np.asarray([fn for _, _, fn in results])
        scores = pos - neg
        ranks = np.empty(len(scores))
        ranks[np.argsort(scores)] = np.arange(len(scores))
        weights = ranks / max(len(scores) - 1, 1) - 0.5
        grad = np.zeros_like(self.theta)
        for (seed, _fp, _fn), w in zip(results, weights):
            eps = np.random.default_rng(seed).standard_normal(
                self.theta.size).astype(np.float32)
            grad += w * eps
        grad /= len(results) * cfg.sigma
        self.theta = (1.0 - cfg.weight_decay) * self.theta + cfg.lr * grad
        self._timesteps_total += 2 * len(results) * cfg.episode_horizon
        return {
            "episode_reward_mean": float(np.mean((pos + neg) / 2.0)),
            "episode_reward_max": float(max(pos.max(), neg.max())),
            "info/grad_norm": float(np.linalg.norm(grad)),
        }

    def get_policy_params(self):
        return _unflatten(self._template, self.theta)

    def get_state(self) -> dict:
        return {"theta": self.theta}

    def set_state(self, state: dict) -> None:
        self.theta = state["theta"]

    def stop(self) -> None:
        import ray_tpu as rt
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
