"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Role parity: rllib/algorithms/bandit (bandit_torch_policy + the
LinUCB/LinTS exploration models): per-arm linear payoff models with
closed-form ridge updates — no gradient loop at all — and exploration by
upper confidence bound (LinUCB) or posterior sampling (LinTS).

Environment protocol (ContextualBanditEnv): ``context() -> ndarray`` and
``pull(arm) -> reward``. The driver keeps per-arm sufficient statistics
(A = I*lambda + sum x x^T, b = sum r x) — batched rank-1 updates in
numpy; a chip adds nothing at these sizes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class ContextualBanditEnv:
    """Protocol + a synthetic linear instance for tests."""

    def __init__(self, num_arms: int = 4, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.num_arms = num_arms
        self.context_dim = context_dim
        self.noise = noise
        self._rng = rng
        self.true_theta = rng.normal(size=(num_arms, context_dim))
        self.true_theta /= np.linalg.norm(self.true_theta, axis=1,
                                          keepdims=True)
        self._ctx: Optional[np.ndarray] = None

    def context(self) -> np.ndarray:
        self._ctx = self._rng.normal(size=self.context_dim)
        self._ctx /= np.linalg.norm(self._ctx)
        return self._ctx

    def pull(self, arm: int) -> float:
        r = float(self.true_theta[arm] @ self._ctx)
        return r + float(self._rng.normal(scale=self.noise))

    def best_reward(self) -> float:
        return float(max(self.true_theta[a] @ self._ctx
                         for a in range(self.num_arms)))


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.exploration = "ucb"     # "ucb" (LinUCB) | "ts" (LinTS)
        self.alpha = 1.0             # UCB width / TS posterior scale
        self.ridge = 1.0
        self.steps_per_iter = 100
        self.env_fn = ContextualBanditEnv
        self.algo_class = Bandit


class Bandit(Algorithm):
    # Bandits have no gym probe / module spec: override the base init.
    def __init__(self, config: BanditConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self.setup()

    def setup(self) -> None:
        cfg: BanditConfig = self.config  # type: ignore[assignment]
        self.env = cfg.env_fn() if callable(cfg.env_fn) else cfg.env_fn
        d = self.env.context_dim
        k = self.env.num_arms
        self.A = np.stack([np.eye(d) * cfg.ridge for _ in range(k)])
        self.b = np.zeros((k, d))
        self._rng = np.random.default_rng(cfg.seed)
        self._regret_total = 0.0

    def _select(self, x: np.ndarray) -> int:
        cfg: BanditConfig = self.config  # type: ignore[assignment]
        scores = np.empty(self.env.num_arms)
        for a in range(self.env.num_arms):
            A_inv = np.linalg.inv(self.A[a])
            theta = A_inv @ self.b[a]
            if cfg.exploration == "ts":
                theta = self._rng.multivariate_normal(
                    theta, cfg.alpha ** 2 * A_inv)
                scores[a] = theta @ x
            else:
                scores[a] = theta @ x + cfg.alpha * np.sqrt(x @ A_inv @ x)
        return int(np.argmax(scores))

    def training_step(self) -> Dict[str, Any]:
        cfg: BanditConfig = self.config  # type: ignore[assignment]
        rewards, regrets = [], []
        for _ in range(cfg.steps_per_iter):
            x = self.env.context()
            arm = self._select(x)
            r = self.env.pull(arm)
            self.A[arm] += np.outer(x, x)
            self.b[arm] += r * x
            rewards.append(r)
            if hasattr(self.env, "best_reward"):
                regrets.append(self.env.best_reward() -
                               float(self.env.true_theta[arm] @ x))
        self._timesteps_total += cfg.steps_per_iter
        self._regret_total += float(np.sum(regrets)) if regrets else 0.0
        out = {"episode_reward_mean": float(np.mean(rewards))}
        if regrets:
            out["info/regret_per_step"] = float(np.mean(regrets))
            out["info/regret_total"] = self._regret_total
        return out

    def get_state(self) -> dict:
        return {"A": self.A, "b": self.b}

    def set_state(self, state: dict) -> None:
        self.A, self.b = state["A"], state["b"]

    def stop(self) -> None:
        pass
