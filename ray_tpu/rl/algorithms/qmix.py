"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Role parity: rllib/algorithms/qmix/qmix.py (+ qmix_policy.py mixer):
per-agent Q-networks (parameter-shared — one jitted forward serves every
agent) feed a MIXING network whose weights are produced by hypernetworks
conditioned on the GLOBAL state, constrained non-negative (abs) so
argmax_a Q_tot decomposes into per-agent argmaxes (the IGM property).
Trained end-to-end on joint transitions with a target network.

Exercises the MultiAgentEnv protocol: the collector steps one env,
records per-STEP joint transitions (all agents' obs/actions, the TEAM
reward, the global state = concat of agent observations).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import MultiAgentEnv
from ray_tpu.rl.module import mlp_apply, mlp_init


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env_fn: Callable[[], MultiAgentEnv] = None  # required
        self.mixing_embed_dim = 16
        self.hidden = 32
        self.buffer_capacity = 20_000
        self.train_batch_size = 64
        self.updates_per_iter = 64
        self.steps_per_iter = 256
        self.target_update_iters = 4
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 3_000
        self.gamma = 0.99
        self.lr = 1e-3
        self.algo_class = QMIX


def _qmix_init(key, obs_dim: int, num_actions: int, n_agents: int,
               state_dim: int, hidden: int, embed: int) -> dict:
    import jax
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    return {
        # shared per-agent Q net
        "q": mlp_init(k1, [obs_dim, hidden, hidden, num_actions]),
        # hypernetworks: state -> mixing weights (non-negative via abs)
        "hyper_w1": mlp_init(k2, [state_dim, embed * n_agents]),
        "hyper_b1": mlp_init(k3, [state_dim, embed]),
        "hyper_w2": mlp_init(k4, [state_dim, embed]),
        "hyper_b2": mlp_init(k5, [state_dim, hidden, 1]),
    }


def _agent_qs(params, obs):  # obs: [B, n_agents, obs_dim]
    import jax.numpy as jnp
    B, n, d = obs.shape
    q = mlp_apply(params["q"], obs.reshape(B * n, d))
    return q.reshape(B, n, -1)


def _mix(params, agent_q, state):
    """agent_q: [B, n] chosen per-agent Qs; state: [B, state_dim] ->
    Q_tot [B]. Monotonic: layer weights pass through abs()."""
    import jax.numpy as jnp
    w1 = jnp.abs(mlp_apply(params["hyper_w1"], state))      # [B, e*n]
    b1 = mlp_apply(params["hyper_b1"], state)               # [B, e]
    B, n = agent_q.shape
    e = b1.shape[1]
    w1 = w1.reshape(B, n, e)
    h = jnp.einsum("bn,bne->be", agent_q, w1) + b1
    h = jnp.where(h > 0, h, 0.01 * h)                       # leaky relu
    w2 = jnp.abs(mlp_apply(params["hyper_w2"], state))      # [B, e]
    b2 = mlp_apply(params["hyper_b2"], state)               # [B, 1]
    return jnp.einsum("be,be->b", h, w2) + b2[:, 0]


class QMIX(Algorithm):
    def __init__(self, config: QMIXConfig):
        # MultiAgentEnv world: no gym probe / module_spec (base init
        # assumes a VectorEnv).
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self.setup()

    def setup(self) -> None:
        import jax
        import optax
        cfg: QMIXConfig = self.config  # type: ignore[assignment]
        if cfg.env_fn is None:
            raise ValueError("QMIXConfig.env_fn (MultiAgentEnv factory) "
                             "is required")
        self.env = cfg.env_fn()
        self._obs = self.env.reset()
        self.agents = sorted(self._obs)
        n = len(self.agents)
        obs_dim = int(np.asarray(self._obs[self.agents[0]]).size)
        self.n_actions = self.env.num_actions
        state_dim = obs_dim * n
        self.params = _qmix_init(
            jax.random.PRNGKey(cfg.seed), obs_dim, self.n_actions, n,
            state_dim, cfg.hidden, cfg.mixing_embed_dim)
        self.target_params = jax.device_get(self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)
        self._buf: List[tuple] = []
        self._ep_return = 0.0
        self.episode_returns: List[float] = []
        self._q_fn = jax.jit(_agent_qs)
        gamma, tx = cfg.gamma, self.tx

        def td_step(params, target, opt_state, batch):
            import jax.numpy as jnp
            obs, acts, rew, nobs, done, state, nstate = batch

            def loss_fn(p):
                q = _agent_qs(p, obs)                        # [B,n,A]
                chosen = jnp.take_along_axis(
                    q, acts[..., None], axis=2)[..., 0]      # [B,n]
                q_tot = _mix(p, chosen, state)
                q_next = _agent_qs(target, nobs).max(axis=2)  # [B,n]
                y = rew + gamma * (1.0 - done) * jax.lax.stop_gradient(
                    _mix(target, q_next, nstate))
                return jnp.mean((q_tot - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            import optax as _ox
            return _ox.apply_updates(params, updates), opt_state, loss

        self._td_step = jax.jit(td_step)
        self._eps_step = 0

    # -- joint-transition collection -------------------------------------
    def _epsilon(self) -> float:
        cfg: QMIXConfig = self.config  # type: ignore[assignment]
        frac = min(1.0, self._eps_step / cfg.epsilon_decay_steps)
        return cfg.epsilon_start + frac * (cfg.epsilon_end -
                                           cfg.epsilon_start)

    def _stack_obs(self, obs_dict) -> np.ndarray:
        return np.stack([np.asarray(obs_dict[a], np.float32)
                         for a in self.agents])

    def _collect(self, steps: int) -> None:
        cfg: QMIXConfig = self.config  # type: ignore[assignment]
        eps = self._epsilon()
        for _ in range(steps):
            o = self._stack_obs(self._obs)           # [n, d]
            q = np.asarray(self._q_fn(self.params, o[None]))[0]  # [n, A]
            greedy = q.argmax(axis=1)
            explore = self._rng.random(len(self.agents)) < eps
            rand = self._rng.integers(0, self.n_actions, len(self.agents))
            acts = np.where(explore, rand, greedy)
            action_dict = {a: int(acts[i])
                           for i, a in enumerate(self.agents)}
            nxt, rew, dones, all_done, _ = self.env.step(action_dict)
            team_r = float(sum(rew.values()))
            done = bool(all_done.get("__all__"))
            no = self._stack_obs(nxt) if not done else o
            self._buf.append((o, acts.astype(np.int32), team_r, no, done))
            if len(self._buf) > cfg.buffer_capacity:
                self._buf.pop(0)
            self._ep_return += team_r
            self._eps_step += 1
            self._timesteps_total += 1
            if done:
                self.episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs = self.env.reset()
            else:
                self._obs = nxt

    def training_step(self) -> Dict[str, Any]:
        import jax
        cfg: QMIXConfig = self.config  # type: ignore[assignment]
        self._collect(cfg.steps_per_iter)
        loss = float("nan")
        if len(self._buf) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iter):
                idx = self._rng.integers(0, len(self._buf),
                                         cfg.train_batch_size)
                rows = [self._buf[i] for i in idx]
                obs = np.stack([r[0] for r in rows])        # [B,n,d]
                acts = np.stack([r[1] for r in rows])
                rew = np.asarray([r[2] for r in rows], np.float32)
                nobs = np.stack([r[3] for r in rows])
                done = np.asarray([r[4] for r in rows], np.float32)
                state = obs.reshape(len(rows), -1)
                nstate = nobs.reshape(len(rows), -1)
                self.params, self.opt_state, loss = self._td_step(
                    self.params, self.target_params, self.opt_state,
                    (obs, acts, rew, nobs, done, state, nstate))
            loss = float(loss)
        if self.iteration % cfg.target_update_iters == 0:
            self.target_params = jax.device_get(self.params)
        recent = self.episode_returns[-20:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else float("nan"),
            "epsilon": self._epsilon(),
            "info/td_loss": loss,
        }

    def get_state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.params),
                "target": self.target_params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target"]
