"""A2C — synchronous advantage actor-critic.

Role parity: rllib/algorithms/a2c/a2c.py. Exact reduction: with ONE SGD
pass over a freshly-collected on-policy batch, the importance ratio
pi/mu == 1 everywhere, so PPO's clipped surrogate collapses to the plain
policy-gradient loss -logp * advantage — A2C IS the single-epoch,
clip-inactive point of the shared PPO learner (the same relationship the
reference exploits by deriving A2C from the policy-gradient family). The
config pins that point; everything (sync sampling, GAE, jitted update,
weight broadcast) reuses the PPO path.
"""

from __future__ import annotations

from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig


class A2CConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        # One pass, whole-batch, clip never active at ratio==1.
        self.num_sgd_iter = 1
        self.sgd_minibatch_size = 0       # 0 -> whole train batch
        self.clip_param = 10.0            # inert at ratio 1
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.lr = 1e-3
        self.algo_class = A2C


class A2C(PPO):
    # sgd_minibatch_size=0 resolves to whole-batch inside the learner
    # (PPOLearner.update) — no config mutation at build time.
    _default_config = A2CConfig
