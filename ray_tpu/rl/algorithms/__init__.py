from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rl.algorithms.impala import Impala, ImpalaConfig
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.algorithms.sac import SAC, SACConfig
from ray_tpu.rl.algorithms.td3 import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rl.algorithms.appo import APPO, APPOConfig
from ray_tpu.rl.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig
from ray_tpu.rl.algorithms.es import ES, ESConfig
from ray_tpu.rl.algorithms.qmix import QMIX, QMIXConfig
from ray_tpu.rl.algorithms.maddpg import (CoopSpreadEnv, MADDPG,
                                          MADDPGConfig)
from ray_tpu.rl.algorithms.bandits import (Bandit, BanditConfig,
                                           ContextualBanditEnv)

__all__ = ["PPO", "PPOConfig", "Impala", "ImpalaConfig", "DQN", "DQNConfig",
           "SAC", "SACConfig", "TD3", "TD3Config", "DDPG", "DDPGConfig",
           "APPO", "APPOConfig", "A2C", "A2CConfig", "CQL", "CQLConfig",
           "ES", "ESConfig", "QMIX", "QMIXConfig", "MADDPG", "MADDPGConfig",
           "CoopSpreadEnv", "Bandit", "BanditConfig", "ContextualBanditEnv"]
