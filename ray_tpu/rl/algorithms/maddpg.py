"""MADDPG: multi-agent DDPG with centralized critics.

Role parity: rllib/algorithms/maddpg/maddpg.py: each agent i owns a
deterministic actor mu_i(o_i) trained through a CENTRALIZED critic
Q_i(o_1..o_n, a_1..a_n) that sees every agent's observation and action
(centralized training, decentralized execution). Target networks +
Polyak averaging, Gaussian exploration noise, joint replay.

Continuous cooperative test env included (CoopSpreadEnv): agents emit
scalar actions and share -|a_i - target| penalties — coordination is
only learnable through the centralized critic.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import MultiAgentEnv
from ray_tpu.rl.module import mlp_apply, mlp_init


class CoopSpreadEnv(MultiAgentEnv):
    """Two agents, scalar actions in [-1, 1]. Each episode draws a target
    t; reward_i = -|a_i - t| - 0.5 * |a_0 - a_1| (hit the target AND
    agree). Observations: [t, agent_one_hot]."""

    def __init__(self, horizon: int = 10, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self.horizon = horizon
        self._t = 0
        self.target = 0.0
        self.num_actions = -1     # continuous
        self.action_dim = 1

    def _obs(self):
        return {"agent_0": np.array([self.target, 1.0, 0.0], np.float32),
                "agent_1": np.array([self.target, 0.0, 1.0], np.float32)}

    def reset(self):
        self._t = 0
        self.target = float(self._rng.uniform(-0.8, 0.8))
        return self._obs()

    def step(self, actions):
        self._t += 1
        a0 = float(np.asarray(actions["agent_0"]).ravel()[0])
        a1 = float(np.asarray(actions["agent_1"]).ravel()[0])
        rew = {
            "agent_0": -abs(a0 - self.target) - 0.5 * abs(a0 - a1),
            "agent_1": -abs(a1 - self.target) - 0.5 * abs(a0 - a1),
        }
        done = self._t >= self.horizon
        return (self._obs(), rew, {a: done for a in rew},
                {"__all__": done}, {})


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env_fn: Callable[[], MultiAgentEnv] = CoopSpreadEnv
        self.hidden = 64
        self.buffer_capacity = 20_000
        self.train_batch_size = 64
        self.updates_per_iter = 64
        self.steps_per_iter = 200
        self.tau = 0.02              # Polyak
        self.noise_scale = 0.3
        self.gamma = 0.95
        self.actor_lr = 3e-4
        self.critic_lr = 1e-3
        self.actor_delay_iters = 2   # critic warms up before actors move
        self.algo_class = MADDPG


class MADDPG(Algorithm):
    def __init__(self, config: MADDPGConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self.setup()

    def setup(self) -> None:
        import jax
        import jax.numpy as jnp
        import optax
        cfg: MADDPGConfig = self.config  # type: ignore[assignment]
        self.env = cfg.env_fn()
        self._obs = self.env.reset()
        self.agents = sorted(self._obs)
        n = len(self.agents)
        obs_dim = int(np.asarray(self._obs[self.agents[0]]).size)
        adim = getattr(self.env, "action_dim", 1)
        key = jax.random.PRNGKey(cfg.seed)
        keys = jax.random.split(key, 2 * n)
        joint = n * obs_dim + n * adim

        def actor_init(k):
            p = mlp_init(k, [obs_dim, cfg.hidden, cfg.hidden, adim])
            # Near-zero head (the standard DDPG trick): initial actions
            # sit at tanh's linear center instead of a saturated extreme
            # a random critic can strand them in.
            p[-1]["w"] = p[-1]["w"] * 0.01
            return p

        self.params = {
            "actors": [actor_init(keys[i]) for i in range(n)],
            "critics": [mlp_init(keys[n + i],
                                 [joint, cfg.hidden, cfg.hidden, 1])
                        for i in range(n)],
        }
        self.target = jax.device_get(self.params)
        self.atx = optax.adam(cfg.actor_lr)
        self.ctx = optax.adam(cfg.critic_lr)
        self.aopt = self.atx.init(self.params["actors"])
        self.copt = self.ctx.init(self.params["critics"])
        self._rng = np.random.default_rng(cfg.seed)
        self._buf: List[tuple] = []
        self.episode_returns: List[float] = []
        self._ep_return = 0.0
        self.n, self.obs_dim, self.adim = n, obs_dim, adim
        gamma, tau = cfg.gamma, cfg.tau
        atx, ctx = self.atx, self.ctx

        def act(actors, obs):   # obs [n, d] -> [n, adim], tanh-squashed
            return jnp.stack([
                jnp.tanh(mlp_apply(actors[i], obs[i][None])[0])
                for i in range(n)])

        self._act = jax.jit(act)

        def critic_in(obs, acts):   # [B,n,d], [B,n,adim] -> [B, joint]
            B = obs.shape[0]
            return jnp.concatenate([obs.reshape(B, -1),
                                    acts.reshape(B, -1)], axis=1)

        def update(params, target, aopt, copt, batch, do_actor):
            obs, acts, rew, nobs, done = batch   # rew [B,n]

            def critic_loss(critics):
                nacts = jnp.stack([
                    jnp.tanh(mlp_apply(target["actors"][i], nobs[:, i]))
                    for i in range(n)], axis=1)
                total = 0.0
                for i in range(n):
                    qi = mlp_apply(critics[i],
                                   critic_in(obs, acts))[:, 0]
                    qn = mlp_apply(target["critics"][i],
                                   critic_in(nobs, nacts))[:, 0]
                    y = rew[:, i] + gamma * (1 - done) * \
                        jax.lax.stop_gradient(qn)
                    total = total + jnp.mean((qi - y) ** 2)
                return total

            closs, cgrads = jax.value_and_grad(critic_loss)(
                params["critics"])
            cupd, copt = ctx.update(cgrads, copt)
            import optax as _ox
            critics = _ox.apply_updates(params["critics"], cupd)

            def actor_loss(actors):
                total = 0.0
                for i in range(n):
                    pre = mlp_apply(actors[i], obs[:, i])
                    ai = jnp.tanh(pre)
                    joint_a = acts.at[:, i].set(ai)
                    total = total - jnp.mean(mlp_apply(
                        critics[i], critic_in(obs, joint_a))[:, 0])
                    # pre-tanh penalty: keeps actions out of the
                    # saturated zero-gradient region
                    total = total + 1e-3 * jnp.mean(pre ** 2)
                return total

            aloss, agrads = jax.value_and_grad(actor_loss)(
                params["actors"])
            aupd, aopt = atx.update(agrads, aopt)
            # actor delay: freeze actors (do_actor=0) while the critic
            # warms up — a random critic's gradient strands tanh actors
            aupd = jax.tree.map(lambda u: u * do_actor, aupd)
            actors = _ox.apply_updates(params["actors"], aupd)
            new = {"actors": actors, "critics": critics}
            tgt = jax.tree.map(lambda t, p: (1 - tau) * t + tau * p,
                               target, new)
            return new, tgt, aopt, copt, closs, aloss

        self._update = jax.jit(update)

    def _stack_obs(self, od) -> np.ndarray:
        return np.stack([np.asarray(od[a], np.float32)
                         for a in self.agents])

    def training_step(self) -> Dict[str, Any]:
        cfg: MADDPGConfig = self.config  # type: ignore[assignment]
        for _ in range(cfg.steps_per_iter):
            o = self._stack_obs(self._obs)
            a = np.asarray(self._act(self.params["actors"], o))
            a = np.clip(a + self._rng.normal(
                scale=cfg.noise_scale, size=a.shape), -1.0, 1.0)
            action_dict = {ag: a[i] for i, ag in enumerate(self.agents)}
            nxt, rew, _dones, all_done, _ = self.env.step(action_dict)
            done = bool(all_done.get("__all__"))
            self._buf.append((
                o, a.astype(np.float32),
                np.asarray([rew[ag] for ag in self.agents], np.float32),
                self._stack_obs(nxt) if not done else o, done))
            if len(self._buf) > cfg.buffer_capacity:
                self._buf.pop(0)
            self._ep_return += float(np.mean(list(rew.values())))
            self._timesteps_total += 1
            if done:
                self.episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs = self.env.reset()
            else:
                self._obs = nxt
        closs = aloss = float("nan")
        if len(self._buf) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iter):
                idx = self._rng.integers(0, len(self._buf),
                                         cfg.train_batch_size)
                rows = [self._buf[i] for i in idx]
                batch = (np.stack([r[0] for r in rows]),
                         np.stack([r[1] for r in rows]),
                         np.stack([r[2] for r in rows]),
                         np.stack([r[3] for r in rows]),
                         np.asarray([r[4] for r in rows], np.float32))
                do_actor = float(self.iteration >= cfg.actor_delay_iters)
                (self.params, self.target, self.aopt, self.copt,
                 closs, aloss) = self._update(
                    self.params, self.target, self.aopt, self.copt, batch,
                    do_actor)
            closs, aloss = float(closs), float(aloss)
        recent = self.episode_returns[-20:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else float("nan"),
            "info/critic_loss": closs,
            "info/actor_loss": aloss,
        }

    def get_state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.params),
                "target": self.target}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target = state["target"]
