"""TD3 — twin-delayed deterministic policy gradient (continuous control).

Role parity: rllib/algorithms/td3/td3.py (TD3Config/TD3: DDPG + twin Q +
delayed policy updates + target policy smoothing). TPU-first: the whole
update — twin critics, (delayed) deterministic actor, polyak targets — is
ONE jitted step; delay is a traced lax.cond on an update counter, so no
python branching inside the compiled program. Actions are tanh-squashed to
the env bounds; exploration adds gaussian noise outside jit (collector
side, numpy), matching the reference's GaussianNoise exploration.

Learning gate: PendulumVectorEnv (env.py) — reward rises from ~-1300
(random) toward ~-200; the CI test asserts a clear improvement threshold.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import episode_stats_of, make_env
from ray_tpu.rl.module import mlp_apply, mlp_init
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.sample_batch import SampleBatch


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.train_batch_size = 128
        self.updates_per_iter = 256
        self.rollout_fragment_length = 64
        self.gamma = 0.99
        self.tau = 0.005                # polyak target mix
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.policy_delay = 2           # critic updates per actor update
        self.target_noise = 0.2         # target policy smoothing sigma
        self.target_noise_clip = 0.5
        self.exploration_noise = 0.1    # rollout gaussian sigma (action units)
        self.twin_q = True
        self.algo_class = TD3


class TD3Learner:
    """Jitted TD3 update: twin critics every step, actor+targets every
    policy_delay-th step (lax.cond keeps it one compiled program)."""

    def __init__(self, module_spec: dict, *, actor_lr: float = 1e-3,
                 critic_lr: float = 1e-3, gamma: float = 0.99,
                 tau: float = 0.005, policy_delay: int = 2,
                 target_noise: float = 0.2, target_noise_clip: float = 0.5,
                 action_low: float = -1.0, action_high: float = 1.0,
                 hiddens=(64, 64), twin_q: bool = True, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        obs_dim = module_spec["obs_dim"]
        act_dim = module_spec.get("action_dim", 1)
        if module_spec.get("num_actions", -1) > 0:
            raise ValueError("TD3 is continuous-only; use DQN/SAC for "
                             "discrete action spaces")
        scale = (action_high - action_low) / 2.0
        mid = (action_high + action_low) / 2.0

        key = jax.random.PRNGKey(seed)
        ka, k1, k2 = jax.random.split(key, 3)
        params = {
            "actor": mlp_init(ka, (obs_dim, *hiddens, act_dim)),
            "q1": mlp_init(k1, (obs_dim + act_dim, *hiddens, 1)),
            "q2": mlp_init(k2, (obs_dim + act_dim, *hiddens, 1)),
        }
        self.params = params
        self.target = jax.device_get(params)
        self.tx_actor = optax.adam(actor_lr)
        self.tx_critic = optax.adam(critic_lr)
        self.opt_actor = self.tx_actor.init(params["actor"])
        self.opt_critic = self.tx_critic.init(
            {"q1": params["q1"], "q2": params["q2"]})
        self._step_count = jnp.zeros((), jnp.int32)
        self._key = jax.random.PRNGKey(seed + 17)

        def act(actor_params, obs):
            raw = mlp_apply(actor_params, obs)
            return jnp.tanh(raw) * scale + mid

        self.act = act

        def q_val(qp, obs, actions):
            if actions.ndim == 1:
                actions = actions[:, None]
            return mlp_apply(qp, jnp.concatenate([obs, actions], axis=-1)
                             )[..., 0]

        tx_actor, tx_critic = self.tx_actor, self.tx_critic

        def update_step(params, target, opt_actor, opt_critic, step_count,
                        key, batch):
            obs, actions = batch[sb.OBS], batch[sb.ACTIONS]
            rew, dones = batch[sb.REWARDS], batch[sb.DONES]
            next_obs = batch[sb.NEXT_OBS]
            if actions.ndim == 1:
                actions = actions[:, None]

            # Target policy smoothing: clipped noise on the target action.
            key, sub = jax.random.split(key)
            noise = jnp.clip(
                jax.random.normal(sub, actions.shape) * target_noise * scale,
                -target_noise_clip * scale, target_noise_clip * scale)
            a_next = jnp.clip(act(target["actor"], next_obs) + noise,
                              action_low, action_high)
            if twin_q:
                q_next = jnp.minimum(
                    q_val(target["q1"], next_obs, a_next),
                    q_val(target["q2"], next_obs, a_next))
            else:  # DDPG: single critic
                q_next = q_val(target["q1"], next_obs, a_next)
            td_target = jax.lax.stop_gradient(
                rew + gamma * (1.0 - dones) * q_next)

            def critic_loss(qps):
                l1 = jnp.mean((q_val(qps["q1"], obs, actions) - td_target)
                              ** 2)
                if not twin_q:
                    return l1
                l2 = jnp.mean((q_val(qps["q2"], obs, actions) - td_target)
                              ** 2)
                return l1 + l2

            qps = {"q1": params["q1"], "q2": params["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(qps)
            cupd, opt_critic = tx_critic.update(cgrads, opt_critic)
            import optax as _optax
            qps = _optax.apply_updates(qps, cupd)
            params = {**params, "q1": qps["q1"], "q2": qps["q2"]}

            def actor_loss(ap):
                return -jnp.mean(q_val(params["q1"], obs, act(ap, obs)))

            def do_actor(_):
                aloss, agrads = jax.value_and_grad(actor_loss)(
                    params["actor"])
                aupd, new_opt = tx_actor.update(agrads, opt_actor)
                new_actor = _optax.apply_updates(params["actor"], aupd)
                new_target = jax.tree_util.tree_map(
                    lambda t, p: t * (1.0 - tau) + p * tau, target,
                    {**params, "actor": new_actor})
                return new_actor, new_opt, new_target, aloss

            def skip_actor(_):
                return (params["actor"], opt_actor, target,
                        jnp.zeros((), jnp.float32))

            step_count = step_count + 1
            actor_p, opt_actor, target, aloss = jax.lax.cond(
                step_count % policy_delay == 0, do_actor, skip_actor,
                operand=None)
            params = {**params, "actor": actor_p}
            return (params, target, opt_actor, opt_critic, step_count, key,
                    {"critic_loss": closs, "actor_loss": aloss})

        self._update = jax.jit(update_step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        (self.params, self.target, self.opt_actor, self.opt_critic,
         self._step_count, self._key, info) = self._update(
            self.params, self.target, self.opt_actor, self.opt_critic,
            self._step_count, self._key, dict(batch))
        return {k: float(v) for k, v in info.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.params["actor"])

    def state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.params),
                "target": jax.device_get(self.target)}

    def set_state(self, st: dict) -> None:
        self.params = st["params"]
        self.target = st["target"]


class TD3Collector:
    """Deterministic policy + gaussian exploration noise (reference's
    GaussianNoise exploration, rllib/utils/exploration)."""

    def __init__(self, env: Any, module_spec: dict, num_envs: int,
                 *, hiddens=(64, 64), noise: float = 0.1, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.env = make_env(env, num_envs=num_envs, seed=seed)
        low, high = self.env.action_low, self.env.action_high
        scale, mid = (high - low) / 2.0, (high + low) / 2.0
        self.low, self.high = low, high
        self.noise = noise * scale
        self.obs = self.env.vector_reset(seed=seed)
        self._rng = np.random.default_rng(seed)
        self._act = jax.jit(
            lambda p, o: jnp.tanh(mlp_apply(p, o)) * scale + mid)

    def collect(self, actor_params, steps: int,
                warmup: bool = False) -> SampleBatch:
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
                                sb.DONES)}
        N = self.env.num_envs
        act_dim = getattr(self.env, "action_dim", 1)
        for _ in range(steps):
            if warmup:
                a = self._rng.uniform(self.low, self.high, (N, act_dim))
            else:
                a = np.asarray(self._act(actor_params, self.obs))
                a = a.reshape(N, act_dim)
                a = np.clip(a + self._rng.normal(0, self.noise, a.shape),
                            self.low, self.high)
            next_obs, rew, done, _ = self.env.vector_step(a)
            rows[sb.OBS].append(self.obs.copy())
            rows[sb.ACTIONS].append(a.astype(np.float32))
            rows[sb.REWARDS].append(rew)
            rows[sb.NEXT_OBS].append(next_obs.copy())
            rows[sb.DONES].append(done)
            self.obs = next_obs
        return SampleBatch({k: np.concatenate(v) for k, v in rows.items()})

    def episode_stats(self) -> dict:
        return episode_stats_of(self.env)


class TD3(Algorithm):
    _default_config = TD3Config

    def setup(self) -> None:
        import ray_tpu as rt

        cfg: TD3Config = self.config  # type: ignore[assignment]
        probe = make_env(cfg.env, num_envs=1, seed=cfg.seed)
        self.learner = TD3Learner(
            self.module_spec, actor_lr=cfg.actor_lr, critic_lr=cfg.critic_lr,
            gamma=cfg.gamma, tau=cfg.tau, policy_delay=cfg.policy_delay,
            target_noise=cfg.target_noise,
            target_noise_clip=cfg.target_noise_clip,
            action_low=probe.action_low, action_high=probe.action_high,
            hiddens=tuple(cfg.model_hiddens), twin_q=cfg.twin_q,
            seed=cfg.seed)
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        collector_cls = rt.remote(TD3Collector)
        self.collectors = [
            collector_cls.options(num_cpus=1).remote(
                cfg.env, self.module_spec, cfg.num_envs_per_worker,
                hiddens=tuple(cfg.model_hiddens),
                noise=cfg.exploration_noise, seed=cfg.seed + i + 1)
            for i in range(cfg.num_rollout_workers)]

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu as rt

        cfg: TD3Config = self.config  # type: ignore[assignment]
        warmup = self._timesteps_total < cfg.learning_starts
        weights = self.learner.get_weights()
        batches = rt.get([c.collect.remote(weights,
                                           cfg.rollout_fragment_length,
                                           warmup=warmup)
                          for c in self.collectors])
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count
        info: Dict[str, float] = {}
        if self._timesteps_total >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                info = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
        stats = rt.get([c.episode_stats.remote() for c in self.collectors])
        rewards = [s["episode_reward_mean"] for s in stats
                   if not np.isnan(s["episode_reward_mean"])]
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "timesteps_total": self._timesteps_total,
            **info,
        }

    def get_state(self) -> dict:
        return {"learner": self.learner.state(),
                "timesteps_total": self._timesteps_total,
                "iteration": self.iteration}

    def set_state(self, state: dict) -> None:
        self.learner.set_state(state["learner"])
        self._timesteps_total = state["timesteps_total"]
        self.iteration = state["iteration"]


class DDPGConfig(TD3Config):
    """DDPG (parity: rllib/algorithms/ddpg) — TD3's degenerate point:
    single critic, no delay, no target smoothing."""

    def __init__(self):
        super().__init__()
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
        self.algo_class = DDPG


class DDPG(TD3):
    _default_config = DDPGConfig
