"""IMPALA-style async actor-critic.

Role parity: rllib/algorithms/impala (async sample RPCs feeding a learner,
impala.py:497-508 LearnerThread role). Sampling is decoupled: each rollout
worker always has one sample RPC in flight; the driver consumes whichever
finishes first (rt.wait), updates with an importance-weighted loss
(clipped-rho correction for the policy lag), and re-dispatches that worker
with fresh weights. The device-side queue of the reference's
MultiGPULearnerThread collapses into the jitted update.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.learner import LearnerGroup, PPOLearner
from ray_tpu.rl.sample_batch import SampleBatch


def async_training_step(inflight: Dict[Any, Any], target: int, update,
                        dispatch) -> Tuple[int, Dict[str, float]]:
    """Shared IMPALA/APPO async driver loop (LearnerThread role): consume
    whichever in-flight sample finishes first, update, re-dispatch that
    worker with fresh weights. ``dispatch(worker)`` must register the
    worker's next sample ref into ``inflight``."""
    import ray_tpu as rt
    count, stats = 0, {}
    while count < target:
        ready, _ = rt.wait(list(inflight), num_returns=1, timeout=600)
        if not ready:
            # Surface a real diagnosis instead of IndexError: every worker
            # stalled past the deadline (dead daemon, hung env, ...).
            raise TimeoutError(
                f"no rollout batch arrived within 600s from "
                f"{len(inflight)} in-flight rollout workers")
        ref = ready[0]
        worker = inflight.pop(ref)
        batch = rt.get(ref)
        count += batch.count
        stats = update(batch)
        dispatch(worker)
    return count, stats


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_rho = 1.0          # V-trace-style IS clip
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.num_sgd_iter = 1        # IMPALA: single pass per batch
        self.sgd_minibatch_size = 512
        self.algo_class = Impala


class ImpalaLearner(PPOLearner):
    """Importance-weighted AC update: like the PPO learner but with a
    one-sided rho clip standing in for V-trace's truncated IS weights
    (full sequence-level V-trace lands with the recurrent stack)."""

    def __init__(self, *, clip_rho: float = 1.0, **kwargs):
        kwargs.setdefault("clip_param", clip_rho)
        super().__init__(**kwargs)


class Impala(Algorithm):
    def setup(self) -> None:
        cfg: ImpalaConfig = self.config  # type: ignore[assignment]
        self.learner_group = LearnerGroup(
            ImpalaLearner,
            dict(module_spec=self.module_spec, lr=cfg.lr,
                 clip_rho=cfg.clip_rho, vf_loss_coeff=cfg.vf_loss_coeff,
                 entropy_coeff=cfg.entropy_coeff,
                 num_sgd_iter=cfg.num_sgd_iter,
                 sgd_minibatch_size=cfg.sgd_minibatch_size, seed=cfg.seed),
            remote=cfg.learner_remote, num_tpus=cfg.learner_num_tpus)
        self.workers = WorkerSet(cfg, self.module_spec)
        self._weights_ref = self.workers.sync_weights(
            self.learner_group.get_weights())
        # Pipeline: every worker keeps exactly one sample() in flight.
        self._inflight: Dict[Any, Any] = {}
        for w in self.workers.workers:
            self._inflight[w.sample.remote(self._weights_ref)] = w

    def training_step(self) -> Dict[str, Any]:
        def dispatch(worker):
            self._weights_ref = self.workers.sync_weights(
                self.learner_group.get_weights())
            self._inflight[worker.sample.remote(self._weights_ref)] = worker

        count, stats = async_training_step(
            self._inflight, self.config.train_batch_size,
            self.learner_group.update, dispatch)
        self._timesteps_total += count
        ep = self.workers.episode_stats()
        means = [s["episode_reward_mean"] for s in ep if s["episodes"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means
            else float("nan"),
            "num_env_steps_sampled": count,
            **{f"info/{k}": v for k, v in stats.items()},
        }

    def get_state(self) -> dict:
        return {"weights": self.learner_group.get_weights()}

    def set_state(self, state: dict) -> None:
        if self.learner_group.remote:
            import ray_tpu as rt
            rt.get(self.learner_group.actor.set_weights.remote(
                state["weights"]))
        else:
            self.learner_group.local.set_weights(state["weights"])
        self._weights_ref = self.workers.sync_weights(state["weights"])

    def stop(self) -> None:
        self.workers.stop()
        self.learner_group.shutdown()
