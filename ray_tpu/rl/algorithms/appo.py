"""APPO — asynchronous PPO with V-trace off-policy correction.

Role parity: rllib/algorithms/appo/appo.py (APPOConfig/APPO: IMPALA's
async sampling architecture + PPO's clipped surrogate, with V-trace
correcting the policy lag between sampler weights and learner weights).
TPU-first: the whole update — current-policy forward, sequence-level
V-trace (rl/vtrace.py lax.scan), clipped surrogate, value + entropy — is
ONE jitted step per arriving worker batch; no learner thread, the async
loop IS the driver (Impala's pattern in impala.py).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.sample_batch import SampleBatch


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.005
        self.rho_bar = 1.0            # V-trace rho truncation
        self.c_bar = 1.0              # V-trace c truncation
        self.grad_clip = 0.5
        self.algo_class = APPO


class APPOLearner:
    """One jitted V-trace + clipped-surrogate update per worker batch."""

    def __init__(self, module_spec: dict, *, lr: float = 3e-4,
                 clip_param: float = 0.2, vf_loss_coeff: float = 0.5,
                 entropy_coeff: float = 0.005, gamma: float = 0.99,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 grad_clip: float = 0.5, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.module import make_module
        from ray_tpu.rl.vtrace import vtrace_returns

        self.module = make_module(module_spec)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.tx.init(self.params)
        module, tx = self.module, self.tx

        def update_fn(params, opt_state, batch, last_obs):
            T, N = batch["rewards_tn"].shape

            def loss_fn(p):
                logp, entropy, value = module.logp_entropy(
                    p, batch[sb.OBS], batch[sb.ACTIONS])
                logp_tn = logp.reshape(T, N)
                value_tn = value.reshape(T, N)
                behavior_tn = batch[sb.ACTION_LOGP].reshape(T, N)
                # Bootstrap with the CURRENT value function so the tail
                # target matches the in-sequence values (no stale mix).
                bootstrap = module.apply(p, last_obs)[1]
                vs, pg_adv = vtrace_returns(
                    behavior_tn, logp_tn, batch["rewards_tn"],
                    value_tn, batch["dones_tn"], bootstrap,
                    gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)
                adv = pg_adv.reshape(-1)
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                ratio = jnp.exp(logp - batch[sb.ACTION_LOGP])
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
                pi_loss = -surr.mean()
                vf_loss = ((value_tn - vs) ** 2).mean()
                ent = entropy.mean()
                total = (pi_loss + vf_loss_coeff * vf_loss
                         - entropy_coeff * ent)
                return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                               "entropy": ent,
                               "mean_rho": ratio.mean()}

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._update = jax.jit(update_fn)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        T, N = batch.rollout_shape
        # Only what the loss reads goes host->device (hot async loop).
        feed = {
            sb.OBS: batch[sb.OBS], sb.ACTIONS: batch[sb.ACTIONS],
            sb.ACTION_LOGP: batch[sb.ACTION_LOGP],
            "rewards_tn": np.asarray(batch[sb.REWARDS]).reshape(T, N),
            "dones_tn": np.asarray(batch[sb.DONES]).reshape(T, N),
        }
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, feed, batch.last_obs)
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.params)

    def set_weights(self, params) -> bool:
        self.params = params
        return True


class APPO(Algorithm):
    _default_config = APPOConfig

    def setup(self) -> None:
        cfg: APPOConfig = self.config  # type: ignore[assignment]
        self.learner = APPOLearner(
            self.module_spec, lr=cfg.lr, clip_param=cfg.clip_param,
            vf_loss_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff, gamma=cfg.gamma,
            rho_bar=cfg.rho_bar, c_bar=cfg.c_bar, grad_clip=cfg.grad_clip,
            seed=cfg.seed)
        self.workers = WorkerSet(cfg, self.module_spec)
        self._weights_ref = self.workers.sync_weights(
            self.learner.get_weights())
        # Async pipeline (impala.py pattern): one STRUCTURED sample in
        # flight per worker; v-trace absorbs the weights lag.
        self._inflight: Dict[Any, Any] = {}
        for w in self.workers.workers:
            self._inflight[w.sample.remote(self._weights_ref,
                                           structured=True)] = w

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rl.algorithms.impala import async_training_step

        def dispatch(worker):
            self._weights_ref = self.workers.sync_weights(
                self.learner.get_weights())
            self._inflight[worker.sample.remote(self._weights_ref,
                                                structured=True)] = worker

        count, stats = async_training_step(
            self._inflight, self.config.train_batch_size,
            self.learner.update, dispatch)
        self._timesteps_total += count
        ep = self.workers.episode_stats()
        means = [s["episode_reward_mean"] for s in ep if s["episodes"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means
            else float("nan"),
            "num_env_steps_sampled": count,
            **{f"info/{k}": v for k, v in stats.items()},
        }

    def get_state(self) -> dict:
        return {"weights": self.learner.get_weights()}

    def set_state(self, state: dict) -> None:
        self.learner.set_weights(state["weights"])
        self._weights_ref = self.workers.sync_weights(state["weights"])

    def stop(self) -> None:
        self.workers.stop()
