"""CQL — conservative Q-learning from offline data (discrete variant).

Role parity: rllib/algorithms/cql/cql.py (CQL = SAC/DQN + a conservative
regularizer keeping Q-values of out-of-dataset actions low, Kumar et al.
2020). Discrete form on the shared Q-module:

    L = TD(double-Q with target net)  +  alpha * CQL(H)
    CQL(H) = E_s[ logsumexp_a Q(s, a) - Q(s, a_data) ]

TPU-first: one jitted update per batch (TD + regularizer + polyak target),
data streamed from the offline JsonReader — no environment interaction
during training; evaluation rolls the greedy policy on the live env.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.offline import BCConfig, JsonReader
from ray_tpu.rl.sample_batch import SampleBatch


class CQLConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.alpha = 1.0              # conservatism weight
        self.tau = 0.005              # polyak target mix
        self.lr = 5e-4
        self.algo_class = CQL


class CQL:
    def __init__(self, config: CQLConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.env import make_env
        from ray_tpu.rl.module import mlp_apply, mlp_init

        self.config = config
        self.reader = JsonReader(config.input_path, seed=config.seed)
        probe = make_env(config.env, num_envs=1, seed=config.seed)
        if probe.num_actions <= 0:
            raise ValueError("the discrete CQL variant needs a discrete "
                             "action space")
        self.num_actions = probe.num_actions
        obs_dim = probe.observation_dim
        hiddens = tuple(config.model_hiddens)

        key = jax.random.PRNGKey(config.seed)
        self.params = {"q": mlp_init(key, (obs_dim, *hiddens,
                                           self.num_actions))}
        self.target = jax.device_get(self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.iteration = 0
        gamma, alpha, tau = config.gamma, config.alpha, config.tau
        tx = self.tx

        def update_fn(params, target, opt_state, batch):
            def loss_fn(p):
                q = mlp_apply(p["q"], batch[sb.OBS])
                qa = q[jnp.arange(q.shape[0]),
                       batch[sb.ACTIONS].astype(jnp.int32)]
                # double-Q target: online argmax, target value
                q_next_online = mlp_apply(p["q"], batch[sb.NEXT_OBS])
                a_star = jnp.argmax(q_next_online, axis=1)
                q_next = mlp_apply(target["q"], batch[sb.NEXT_OBS])
                td_target = jax.lax.stop_gradient(
                    batch[sb.REWARDS] + gamma * (1 - batch[sb.DONES]) *
                    q_next[jnp.arange(a_star.shape[0]), a_star])
                td_loss = jnp.mean((qa - td_target) ** 2)
                # conservative penalty: push down unseen actions' Q
                cql_loss = jnp.mean(
                    jax.scipy.special.logsumexp(q, axis=1) - qa)
                total = td_loss + alpha * cql_loss
                return total, {"td_loss": td_loss, "cql_loss": cql_loss,
                               "mean_q": qa.mean()}

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            target_new = jax.tree_util.tree_map(
                lambda t, o: t * (1.0 - tau) + o * tau, target, params)
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, target_new, opt_state, stats

        self._update = jax.jit(update_fn)
        self._mlp_apply = mlp_apply

    def train(self) -> Dict[str, Any]:
        stats: Dict[str, Any] = {}
        for _ in range(self.config.updates_per_iter):
            b = self.reader.sample(self.config.train_batch_size)
            batch = {
                sb.OBS: np.asarray(b[sb.OBS], np.float32),
                sb.ACTIONS: np.asarray(b[sb.ACTIONS]),
                sb.REWARDS: np.asarray(b[sb.REWARDS], np.float32),
                sb.NEXT_OBS: np.asarray(b[sb.NEXT_OBS], np.float32),
                sb.DONES: np.asarray(b[sb.DONES], np.float32),
            }
            self.params, self.target, self.opt_state, stats = self._update(
                self.params, self.target, self.opt_state, batch)
        self.iteration += 1
        return {k: float(v) for k, v in stats.items()} | {
            "training_iteration": self.iteration}

    def evaluate(self, num_episodes: int = 10) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.env import make_env
        venv = make_env(self.config.env, num_envs=8,
                        seed=self.config.seed + 1)
        act = jax.jit(lambda p, o: jnp.argmax(
            self._mlp_apply(p["q"], o), axis=-1))
        obs = venv.vector_reset(seed=self.config.seed + 1)
        while len(venv.completed_returns) < num_episodes:
            obs, _, _, _ = venv.vector_step(
                np.asarray(act(self.params, obs)))
        returns = venv.completed_returns[:num_episodes]
        return {"episode_reward_mean": float(np.mean(returns))}
