"""PPO (parity: rllib/algorithms/ppo — sync sample + clipped-surrogate
minibatch SGD; the 3.5 call stack of SURVEY.md with the Learner as a jitted
update instead of torch towers)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.learner import LearnerGroup, PPOLearner
from ray_tpu.rl.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 6
        self.sgd_minibatch_size = 128
        self.grad_clip = 0.5
        self.algo_class = PPO


class PPO(Algorithm):
    def setup(self) -> None:
        cfg: PPOConfig = self.config  # type: ignore[assignment]
        self.learner_group = LearnerGroup(
            PPOLearner,
            dict(module_spec=self.module_spec, lr=cfg.lr,
                 clip_param=cfg.clip_param, vf_clip_param=cfg.vf_clip_param,
                 vf_loss_coeff=cfg.vf_loss_coeff,
                 entropy_coeff=cfg.entropy_coeff,
                 num_sgd_iter=cfg.num_sgd_iter,
                 sgd_minibatch_size=cfg.sgd_minibatch_size,
                 grad_clip=cfg.grad_clip, seed=cfg.seed),
            remote=cfg.learner_remote, num_tpus=cfg.learner_num_tpus)
        self.workers = WorkerSet(cfg, self.module_spec)
        self._weights_ref = self.workers.sync_weights(
            self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        # 1. synchronous parallel sampling (rollout_ops role)
        batches = self.workers.sample(self._weights_ref)
        train_batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += train_batch.count
        # 2. learner update (jitted SGD epochs)
        stats = self.learner_group.update(train_batch)
        # 3. broadcast new weights through the object store
        self._weights_ref = self.workers.sync_weights(
            self.learner_group.get_weights())
        ep = self.workers.episode_stats()
        means = [s["episode_reward_mean"] for s in ep
                 if s["episodes"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means
            else float("nan"),
            "episodes_total": int(sum(s["episodes"] for s in ep)),
            "num_env_steps_sampled": train_batch.count,
            **{f"info/{k}": v for k, v in stats.items()},
        }

    def get_state(self) -> dict:
        return {"weights": self.learner_group.get_weights()}

    def set_state(self, state: dict) -> None:
        if self.learner_group.remote:
            import ray_tpu as rt
            rt.get(self.learner_group.actor.set_weights.remote(
                state["weights"]))
        else:
            self.learner_group.local.set_weights(state["weights"])
        self._weights_ref = self.workers.sync_weights(state["weights"])

    def stop(self) -> None:
        self.workers.stop()
        self.learner_group.shutdown()
