"""SAC — soft actor-critic on the Learner/RLModule stack.

Role parity: rllib/algorithms/sac/sac.py (SACConfig/SAC) — twin soft
Q-functions with target networks, entropy-regularized policy, automatic
temperature tuning. Discrete action spaces use the exact-expectation
variant (SAC-Discrete), so the same CartPole gate as the other algorithms
applies; continuous (1-D gaussian) spaces use the reparameterized sampled
update. TPU-first: the whole update (twin Q + policy + alpha + target
polyak) is ONE jitted step; off-policy data comes from the shared
ReplayBuffer the way DQN's does.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.algorithms.dqn import DQNCollector
from ray_tpu.rl.module import mlp_apply, mlp_init
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.sample_batch import SampleBatch


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.train_batch_size = 256
        # Off-policy: a high update:sample ratio is what makes SAC
        # sample-efficient (tuned on the CartPole gate: reward>=100 within
        # ~10k env steps at these settings, across seeds 0-3).
        self.updates_per_iter = 192
        self.rollout_fragment_length = 32
        self.gamma = 0.99
        # Polyak mix: at 192 updates/iter a 0.005 mix leaves the targets
        # lagging the online Q far enough that the bellman bootstrap stalls
        # ~95 reward inside the CI budget; 0.03 tracks fast enough to clear
        # the gate while still damping target oscillation.
        self.tau = 0.03
        self.lr = 1e-3
        self.initial_alpha = 0.2
        self.autotune_alpha = True
        self.target_entropy_scale = 0.4   # × log|A|
        self.algo_class = SAC


class SACLearner:
    """Jitted SAC update (twin Q + policy + temperature, one step)."""

    def __init__(self, module_spec: dict, *, lr: float = 1e-3,
                 gamma: float = 0.99, tau: float = 0.03,
                 initial_alpha: float = 0.2, autotune_alpha: bool = True,
                 target_entropy_scale: float = 0.4, seed: int = 0):
        # Defaults mirror SACConfig (the tuned CartPole-gate values); the
        # config remains the single place they are reasoned about.
        import jax
        import jax.numpy as jnp
        import optax

        obs_dim = module_spec["obs_dim"]
        self.num_actions = module_spec["num_actions"]
        hiddens = tuple(module_spec.get("hiddens", (64, 64)))
        if self.num_actions <= 0:
            raise NotImplementedError(
                "SACLearner currently covers discrete action spaces "
                "(SAC-Discrete); continuous support tracks the gaussian "
                "RLModule head")
        A = self.num_actions
        target_entropy = target_entropy_scale * float(np.log(A))

        key = jax.random.PRNGKey(seed)
        kp, k1, k2 = jax.random.split(key, 3)
        self.params = {
            "pi": mlp_init(kp, (obs_dim, *hiddens, A)),
            "q1": mlp_init(k1, (obs_dim, *hiddens, A)),
            "q2": mlp_init(k2, (obs_dim, *hiddens, A)),
            "log_alpha": jnp.asarray(float(np.log(initial_alpha))),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        tx = self.tx

        def losses(params, target, batch):
            obs, acts = batch[sb.OBS], batch[sb.ACTIONS].astype(jnp.int32)
            rew, done = batch[sb.REWARDS], batch[sb.DONES].astype(jnp.float32)
            nxt = batch[sb.NEXT_OBS]
            alpha = jnp.exp(params["log_alpha"])
            idx = jnp.arange(obs.shape[0])

            # -- twin-Q bellman target (exact expectation over π(.|s')) --
            logits_n = mlp_apply(params["pi"], nxt)
            logp_n = jax.nn.log_softmax(logits_n)
            p_n = jnp.exp(logp_n)
            q1_t = mlp_apply(target["q1"], nxt)
            q2_t = mlp_apply(target["q2"], nxt)
            minq = jnp.minimum(q1_t, q2_t)
            v_next = jnp.sum(p_n * (minq - alpha * logp_n), axis=-1)
            y = jax.lax.stop_gradient(rew + gamma * (1.0 - done) * v_next)

            q1 = mlp_apply(params["q1"], obs)[idx, acts]
            q2 = mlp_apply(params["q2"], obs)[idx, acts]
            q_loss = ((q1 - y) ** 2).mean() + ((q2 - y) ** 2).mean()

            # -- policy: minimize E[π(α logπ - minQ)] --------------------
            logits = mlp_apply(params["pi"], obs)
            logp = jax.nn.log_softmax(logits)
            p = jnp.exp(logp)
            q1_pi = jax.lax.stop_gradient(mlp_apply(params["q1"], obs))
            q2_pi = jax.lax.stop_gradient(mlp_apply(params["q2"], obs))
            minq_pi = jnp.minimum(q1_pi, q2_pi)
            pi_loss = jnp.sum(
                p * (jax.lax.stop_gradient(alpha) * logp - minq_pi),
                axis=-1).mean()
            entropy = -jnp.sum(p * logp, axis=-1).mean()

            # -- temperature --------------------------------------------
            if autotune_alpha:
                alpha_loss = -(params["log_alpha"] *
                               jax.lax.stop_gradient(
                                   -entropy + target_entropy)).mean()
            else:
                alpha_loss = 0.0
            total = q_loss + pi_loss + alpha_loss
            return total, {"q_loss": q_loss, "policy_loss": pi_loss,
                           "alpha": alpha, "entropy": entropy,
                           "mean_q": q1.mean()}

        def update_step(params, target, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                losses, has_aux=True)(params, target, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                target, {"q1": params["q1"], "q2": params["q2"]})
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, target, opt_state, stats

        self._update = jax.jit(update_step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.target, self.opt_state, stats = self._update(
            self.params, self.target, self.opt_state, dict(batch))
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        import jax
        # Collectors sample from π via the RLModule "pi"/"vf" layout; SAC
        # has no vf tower, so export pi plus a dummy scalar head shape.
        return jax.device_get(self.params)

    def set_weights(self, params) -> bool:
        self.params = params
        return True


class _SACCollector(DQNCollector):
    """Boltzmann (softmax-policy) collector: samples a~π(.|s) from the SAC
    policy tower — reuses the DQN vector-env machinery with the policy
    logits in place of Q-values and temperature-1 sampling."""

    def collect(self, params, steps: int, epsilon: float = 0.0) -> SampleBatch:
        import jax

        N = self.env.num_envs
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
                                sb.DONES)}
        for _ in range(steps):
            logits = np.asarray(self._q_fn({"pi": params["pi"]}, self.obs))
            z = logits - logits.max(axis=1, keepdims=True)
            p = np.exp(z)
            p /= p.sum(axis=1, keepdims=True)
            actions = np.array([self._rng.choice(p.shape[1], p=row)
                                for row in p])
            next_obs, rew, done, _ = self.env.vector_step(actions)
            rows[sb.OBS].append(self.obs.copy())
            rows[sb.ACTIONS].append(actions)
            rows[sb.REWARDS].append(rew)
            rows[sb.NEXT_OBS].append(next_obs.copy())
            rows[sb.DONES].append(done)
            self.obs = next_obs
        return SampleBatch({
            k: np.concatenate(v) if v[0].ndim else np.stack(v).reshape(-1)
            for k, v in ((k, rows[k]) for k in rows)})


class SAC(Algorithm):
    _default_config = SACConfig

    def setup(self) -> None:
        import jax
        cfg = self.config
        self.learner = SACLearner(
            self.module_spec, lr=cfg.lr, gamma=cfg.gamma, tau=cfg.tau,
            initial_alpha=cfg.initial_alpha,
            autotune_alpha=cfg.autotune_alpha,
            target_entropy_scale=cfg.target_entropy_scale, seed=cfg.seed)
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.collector = _SACCollector(
            cfg.env, self.module_spec, cfg.num_envs_per_worker,
            seed=cfg.seed)
        # collector applies mlp over the "pi" tower
        self.collector._q_fn = jax.jit(
            lambda p, o: mlp_apply(p["pi"], o))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.collector.collect(self.learner.params,
                                       cfg.rollout_fragment_length)
        self.buffer.add(batch)
        self._timesteps_total += batch.count
        stats: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                stats = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
        ep = self.collector.episode_stats()
        stats["episode_reward_mean"] = (
            ep["episode_reward_mean"] if ep["episodes"] else 0.0)
        stats["num_env_steps_sampled"] = self._timesteps_total
        return stats

    def get_state(self) -> dict:
        return {"params": self.learner.params,
                "target": self.learner.target}

    def set_state(self, state: dict) -> None:
        self.learner.params = state["params"]
        self.learner.target = state["target"]
