"""DQN (parity: rllib/algorithms/dqn — replay buffer + target network +
double-Q update; epsilon-greedy exploration on vectorized envs)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import episode_stats_of, make_env
from ray_tpu.rl.module import make_module, mlp_apply, mlp_init
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.sample_batch import SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 1000
        self.target_update_freq = 500   # env steps between target syncs
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 10_000
        self.train_batch_size = 32
        self.updates_per_iter = 64
        self.gamma = 0.99
        self.lr = 5e-4
        self.algo_class = DQN


class DQNCollector:
    """Actor: epsilon-greedy stepping of a vector env, emitting
    (s, a, r, s', done) transitions."""

    def __init__(self, env: Any, module_spec: dict, num_envs: int,
                 seed: int = 0):
        import jax
        self.env = make_env(env, num_envs=num_envs, seed=seed)
        self.module = make_module(module_spec)
        self.obs = self.env.vector_reset(seed=seed)
        self._rng = np.random.default_rng(seed)
        self._q_fn = jax.jit(lambda p, o: self.module.apply(p, o)[0])
        self.params = None

    def collect(self, params, steps: int, epsilon: float) -> SampleBatch:
        self.params = params
        N = self.env.num_envs
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
                                sb.DONES)}
        for _ in range(steps):
            q = np.asarray(self._q_fn(self.params, self.obs))
            greedy = q.argmax(axis=1)
            explore = self._rng.random(N) < epsilon
            random_a = self._rng.integers(0, q.shape[1], N)
            actions = np.where(explore, random_a, greedy)
            next_obs, rew, done, _ = self.env.vector_step(actions)
            rows[sb.OBS].append(self.obs.copy())
            rows[sb.ACTIONS].append(actions)
            rows[sb.REWARDS].append(rew)
            rows[sb.NEXT_OBS].append(next_obs.copy())
            rows[sb.DONES].append(done)
            self.obs = next_obs
        return SampleBatch({k: np.concatenate(v) for k, v in rows.items()})

    def episode_stats(self) -> dict:
        return episode_stats_of(self.env)


class DQN(Algorithm):
    def setup(self) -> None:
        import jax
        import jax.numpy as jnp
        import optax
        import ray_tpu as rt

        cfg: DQNConfig = self.config  # type: ignore[assignment]
        self.module = make_module(self.module_spec)
        self.params = self.module.init(jax.random.PRNGKey(cfg.seed))
        self.target_params = jax.device_get(self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self._epsilon_step = 0
        collector_cls = rt.remote(DQNCollector)
        self.collectors = [
            collector_cls.options(num_cpus=1).remote(
                cfg.env, self.module_spec, cfg.num_envs_per_worker,
                seed=cfg.seed + i + 1)
            for i in range(cfg.num_rollout_workers)]
        module, tx, gamma = self.module, self.tx, cfg.gamma

        def td_step(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = module.apply(p, batch[sb.OBS])[0]
                qa = q[jnp.arange(q.shape[0]),
                       batch[sb.ACTIONS].astype(jnp.int32)]
                # double-Q: online net argmax, target net value
                q_next_online = module.apply(p, batch[sb.NEXT_OBS])[0]
                a_star = jnp.argmax(q_next_online, axis=1)
                q_next_target = module.apply(target_params,
                                             batch[sb.NEXT_OBS])[0]
                target = batch[sb.REWARDS] + gamma * (1 - batch[sb.DONES]) * \
                    q_next_target[jnp.arange(a_star.shape[0]), a_star]
                target = jax.lax.stop_gradient(target)
                return jnp.mean((qa - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        self._td_step = jax.jit(td_step)

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.config  # type: ignore[assignment]
        frac = min(1.0, self._epsilon_step / cfg.epsilon_decay_steps)
        return cfg.epsilon_start + frac * (cfg.epsilon_end -
                                           cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import ray_tpu as rt
        cfg: DQNConfig = self.config  # type: ignore[assignment]
        weights = jax.device_get(self.params)
        eps = self._epsilon()
        batches = rt.get([c.collect.remote(weights,
                                           cfg.rollout_fragment_length, eps)
                          for c in self.collectors], timeout=600)
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count
            self._epsilon_step += b.count
        loss = float("nan")
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.params, self.opt_state, loss = self._td_step(
                    self.params, self.target_params, self.opt_state,
                    dict(mb))
            if self._timesteps_total % cfg.target_update_freq < \
                    cfg.rollout_fragment_length * cfg.num_rollout_workers \
                    * cfg.num_envs_per_worker:
                self.target_params = jax.device_get(self.params)
            loss = float(loss)
        ep = rt.get([c.episode_stats.remote() for c in self.collectors],
                    timeout=600)
        means = [s["episode_reward_mean"] for s in ep if s["episodes"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means
            else float("nan"),
            "epsilon": eps,
            "info/td_loss": loss,
        }

    def get_state(self) -> dict:
        import jax
        return {"params": jax.device_get(self.params),
                "target": self.target_params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target"]

    def stop(self) -> None:
        import ray_tpu as rt
        for c in self.collectors:
            try:
                rt.kill(c)
            except Exception:
                pass
