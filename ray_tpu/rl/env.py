"""Environments: vectorized-first.

Role parity: rllib/env — BaseEnv (base_env.py:18), VectorEnv
(vector_env.py:23), MultiAgentEnv (multi_agent_env.py:30), gym wrappers
(env/wrappers/). TPU-first: the native representation is a *vectorized*
env stepping N sub-envs as batched numpy — policy forwards are one batched
(jit-able) call instead of N python loops.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """N synchronized sub-envs; auto-resets finished sub-envs."""

    num_envs: int
    observation_dim: int
    num_actions: int                # discrete; -1 => continuous
    action_dim: int = 1             # continuous action dims (Box envs)
    action_low = -1.0               # bounds: scalar or per-dim array [k]
    action_high = 1.0


def episode_stats_of(env) -> dict:
    """Shared reward-window stats for collectors (rollout_worker metrics
    role): mean over the last 100 completed episodes."""
    rets = getattr(env, "completed_returns", [])
    if not rets:
        return {"episode_reward_mean": float("nan"), "episodes": 0}
    return {"episode_reward_mean": float(np.mean(rets[-100:])),
            "episodes": len(rets)}

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def vector_step(self, actions: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict]:
        """-> (obs [N, D], rewards [N], dones [N], infos)."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Pure-numpy vectorized CartPole-v1 dynamics (classic control task;
    same physics constants as the standard benchmark), used for learning
    gates without per-env python object overhead."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    TOTAL_MASS = CART_MASS + POLE_MASS
    LENGTH = 0.5
    POLEMASS_LENGTH = POLE_MASS * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 16, seed: int = 0):
        self.num_envs = num_envs
        self.observation_dim = 4
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self.episode_returns = np.zeros(num_envs)
        self.completed_returns: list = []

    def _reset_indices(self, idx: np.ndarray) -> None:
        self._state[idx] = self._rng.uniform(-0.05, 0.05, (len(idx), 4))
        self._steps[idx] = 0
        self.episode_returns[idx] = 0.0

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_indices(np.arange(self.num_envs))
        return self._state.astype(np.float32).copy()

    def vector_step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.POLEMASS_LENGTH * theta_dot ** 2 * sintheta) \
            / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.POLE_MASS * costheta ** 2
                           / self.TOTAL_MASS))
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta \
            / self.TOTAL_MASS
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1
        terminated = (np.abs(x) > self.X_LIMIT) | \
            (np.abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        dones = terminated | truncated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        self.episode_returns += rewards
        if dones.any():
            self.completed_returns.extend(
                self.episode_returns[dones].tolist())
            self.completed_returns = self.completed_returns[-200:]
            self._reset_indices(np.nonzero(dones)[0])
        return (self._state.astype(np.float32).copy(), rewards,
                dones.astype(np.float32), {})


class GymVectorEnv(VectorEnv):
    """Wraps N gymnasium envs (parity: env/vector_env.py sync vectorization)."""

    def __init__(self, env_id: str, num_envs: int = 8, seed: int = 0,
                 **env_kwargs):
        import gymnasium as gym
        self.envs = [gym.make(env_id, **env_kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        space = self.envs[0].observation_space
        self.observation_dim = int(np.prod(space.shape))
        act = self.envs[0].action_space
        self.num_actions = getattr(act, "n", -1)
        if self.num_actions < 0:  # Box space: keep PER-DIM bounds
            self.action_dim = int(np.prod(act.shape))
            self.action_low = np.asarray(act.low, np.float32).reshape(-1)
            self.action_high = np.asarray(act.high, np.float32).reshape(-1)
        self._seed = seed
        self.episode_returns = np.zeros(num_envs)
        self.completed_returns: list = []

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs = [e.reset(seed=(seed or self._seed) + i)[0].reshape(-1)
               for i, e in enumerate(self.envs)]
        self.episode_returns[:] = 0
        return np.stack(obs).astype(np.float32)

    def vector_step(self, actions: np.ndarray):
        obs_out, rewards, dones = [], [], []
        for i, (e, a) in enumerate(zip(self.envs, actions)):
            obs, r, term, trunc, _ = e.step(
                int(a) if self.num_actions > 0 else a)
            self.episode_returns[i] += r
            done = term or trunc
            if done:
                self.completed_returns.append(self.episode_returns[i])
                self.completed_returns = self.completed_returns[-200:]
                self.episode_returns[i] = 0
                obs = e.reset()[0]
            obs_out.append(np.reshape(obs, -1))
            rewards.append(r)
            dones.append(float(done))
        return (np.stack(obs_out).astype(np.float32),
                np.array(rewards, dtype=np.float32),
                np.array(dones, dtype=np.float32), {})


class PendulumVectorEnv(VectorEnv):
    """Pure-numpy vectorized Pendulum-v1 dynamics (standard constants):
    the continuous-control learning gate (TD3/continuous-SAC), mirroring
    CartPoleVectorEnv's role for the discrete algos."""

    G = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    MAX_STEPS = 200

    def __init__(self, num_envs: int = 16, seed: int = 0):
        self.num_envs = num_envs
        self.observation_dim = 3          # (cos th, sin th, thdot)
        self.num_actions = -1
        self.action_dim = 1
        self.action_low = -self.MAX_TORQUE
        self.action_high = self.MAX_TORQUE
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros(num_envs)
        self._thdot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self.episode_returns = np.zeros(num_envs)
        self.completed_returns: list = []

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._th), np.sin(self._th),
                         self._thdot], axis=-1).astype(np.float32)

    def _reset_indices(self, idx: np.ndarray) -> None:
        self._th[idx] = self._rng.uniform(-np.pi, np.pi, idx.shape)
        self._thdot[idx] = self._rng.uniform(-1.0, 1.0, idx.shape)
        self._steps[idx] = 0

    def vector_reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_indices(np.arange(self.num_envs))
        self.episode_returns[:] = 0
        return self._obs()

    def vector_step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, dtype=np.float64).reshape(-1),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th, thdot = self._th, self._thdot
        angle = ((th + np.pi) % (2 * np.pi)) - np.pi
        costs = angle ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        newthdot = thdot + (3 * self.G / (2 * self.LENGTH) * np.sin(th)
                            + 3.0 / (self.MASS * self.LENGTH ** 2) * u
                            ) * self.DT
        newthdot = np.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._th = th + newthdot * self.DT
        self._thdot = newthdot
        self._steps += 1
        rewards = (-costs).astype(np.float32)
        self.episode_returns += rewards
        dones = self._steps >= self.MAX_STEPS
        if dones.any():
            idx = np.nonzero(dones)[0]
            self.completed_returns.extend(self.episode_returns[idx].tolist())
            self.completed_returns = self.completed_returns[-200:]
            self.episode_returns[idx] = 0
            self._reset_indices(idx)
        return (self._obs(), rewards, dones.astype(np.float32), {})


class MultiAgentEnv:
    """Dict-keyed multi-agent protocol (parity: multi_agent_env.py:30).
    reset() -> {agent: obs}; step({agent: action}) ->
    ({agent: obs}, {agent: r}, {agent: done}, {"__all__": done}, infos)."""

    def reset(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError


def make_env(env: Any, num_envs: int, seed: int = 0) -> VectorEnv:
    if isinstance(env, VectorEnv):
        return env
    if callable(env):
        out = env(num_envs=num_envs, seed=seed)
        if not isinstance(out, VectorEnv):
            raise TypeError("env factory must return a VectorEnv")
        return out
    if env in ("CartPole-v1", "CartPole"):
        return CartPoleVectorEnv(num_envs=num_envs, seed=seed)
    if env in ("Pendulum-v1", "Pendulum"):
        return PendulumVectorEnv(num_envs=num_envs, seed=seed)
    if isinstance(env, str):
        return GymVectorEnv(env, num_envs=num_envs, seed=seed)
    raise TypeError(f"cannot build an env from {env!r}")
