"""ray_tpu.rl — reinforcement-learning library (RLlib-equivalent core).

Parity surface: reference rllib/ — Algorithm/AlgorithmConfig
(algorithms/algorithm.py:149, algorithm_config.py:117), RolloutWorker +
WorkerSet (evaluation/), RLModule/Learner/LearnerGroup (core/), SampleBatch
(policy/sample_batch.py:96), vector/multi-agent envs (env/), replay buffers
(utils/replay_buffers). TPU-first: policies are pure-jax modules, rollout
forwards are one jitted batched call per vector-env step, and the learner
update is a single pjit-able function (DP gradient psum compiled by XLA).
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, WorkerSet
from ray_tpu.rl.env import (CartPoleVectorEnv, GymVectorEnv, MultiAgentEnv,
                            VectorEnv, make_env)
from ray_tpu.rl.learner import LearnerGroup, PPOLearner
from ray_tpu.rl.module import RLModule
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.rollout import RolloutWorker, compute_gae
from ray_tpu.rl.sample_batch import SampleBatch

__all__ = ["Algorithm", "AlgorithmConfig", "WorkerSet", "VectorEnv",
           "CartPoleVectorEnv", "GymVectorEnv", "MultiAgentEnv", "make_env",
           "RLModule", "RolloutWorker", "compute_gae", "SampleBatch",
           "PPOLearner", "LearnerGroup", "ReplayBuffer",
           "PrioritizedReplayBuffer"]
