"""SampleBatch: columnar trajectory container.

Role parity: rllib/policy/sample_batch.py:96 — a dict of parallel arrays
(obs, actions, rewards, dones, logp, value targets, advantages) with
concat/shuffle/minibatch helpers. Kept as plain numpy on the host; the
learner device_puts whole minibatches (contiguous, static shapes) so XLA
sees fixed-shape updates.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in keys})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        idx = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[idx] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch({k: np.asarray(v)[start:start + size]
                               for k, v in self.items()})

    def slice_rows(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: np.asarray(v)[start:end]
                            for k, v in self.items()})
