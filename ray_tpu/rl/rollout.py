"""RolloutWorker: CPU actor stepping a vectorized env with a jitted policy.

Role parity: rllib/evaluation/rollout_worker.py:166 (sample():879) +
env_runner_v2.py — but the inner loop is one jitted batched forward per
step over the whole vector env (no per-env python policy calls), and GAE
(postprocessing.py role) is computed vectorized over the [T, N] rollout.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.env import episode_stats_of, make_env
from ray_tpu.rl.module import make_module
from ray_tpu.rl.sample_batch import SampleBatch


def compute_gae(rewards, values, dones, last_value, gamma: float,
                lam: float):
    """Vectorized GAE over [T, N] arrays -> (advantages, value_targets)."""
    T, N = rewards.shape
    adv = np.zeros((T, N), dtype=np.float32)
    lastgaelam = np.zeros(N, dtype=np.float32)
    for t in reversed(range(T)):
        nextvalue = last_value if t == T - 1 else values[t + 1]
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * nextvalue * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
    return adv, adv + values


class RolloutWorker:
    """One sampling actor (spawned with JAX_PLATFORMS=cpu by the worker
    pool, so policy forwards jit onto host CPU)."""

    def __init__(self, env: Any, module_spec: dict, rollout_length: int,
                 num_envs: int, gamma: float, lam: float, seed: int = 0):
        import jax
        self.env = make_env(env, num_envs=num_envs, seed=seed)
        self.module = make_module(module_spec)
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.lam = lam
        self.key = jax.random.PRNGKey(seed)
        self.obs = self.env.vector_reset(seed=seed)
        self._sample_fn = jax.jit(self.module.sample_actions)
        self._value_fn = jax.jit(
            lambda p, o: self.module.apply(p, o)[1])
        self.params = None

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    def sample(self, params: Optional[Any] = None,
               structured: bool = False) -> SampleBatch:
        """Collect rollout_length * num_envs transitions with GAE.

        structured=True skips GAE and attaches the [T, N] layout + the
        bootstrap value as batch attributes — the learner-side V-trace
        path (APPO/IMPALA) computes its own off-policy-corrected targets
        from the behavior logps."""
        import jax
        if params is not None:
            self.params = params
        T, N = self.rollout_length, self.env.num_envs
        obs_buf = np.empty((T, N, self.env.observation_dim), np.float32)
        # Continuous modules with action_dim>1 emit [N, k] actions.
        act_dim = getattr(self.module, "action_dim", 1)
        act_shape = (T, N) if (self.module.num_actions > 0 or act_dim == 1) \
            else (T, N, act_dim)
        act_buf = np.empty(act_shape, np.float32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)
        for t in range(T):
            self.key, sub = jax.random.split(self.key)
            actions, logp, value = self._sample_fn(self.params, self.obs, sub)
            actions = np.asarray(actions)
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, rew_buf[t], done_buf[t], _ = \
                self.env.vector_step(actions)
        flat = lambda x: x.reshape(T * N, *x.shape[2:])
        batch = SampleBatch({
            sb.OBS: flat(obs_buf), sb.ACTIONS: flat(act_buf),
            sb.REWARDS: flat(rew_buf), sb.DONES: flat(done_buf),
            sb.ACTION_LOGP: flat(logp_buf),
        })
        if structured:
            # The learner bootstraps with ITS OWN value function — ship the
            # final observation, not a stale behavior-policy value (the lag
            # V-trace's rho/c clipping does not correct for values).
            batch.rollout_shape = (T, N)
            batch.last_obs = np.asarray(self.obs, np.float32)
            return batch
        last_value = np.asarray(self._value_fn(self.params, self.obs))
        adv, targets = compute_gae(rew_buf, val_buf, done_buf, last_value,
                                   self.gamma, self.lam)
        batch[sb.VF_PREDS] = flat(val_buf)
        batch[sb.ADVANTAGES] = flat(adv)
        batch[sb.VALUE_TARGETS] = flat(targets)
        return batch

    def episode_stats(self) -> dict:
        return episode_stats_of(self.env)

    def ping(self) -> str:
        return "pong"
