"""Replay buffers (parity: rllib/utils/replay_buffers — ReplayBuffer +
prioritized variant with sum-tree sampling)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring buffer over flat transition columns."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity, *v.shape[1:]),
                                         v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (alpha) + importance weights (beta)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prio = np.zeros(capacity, dtype=np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(batch)
        self._prio[idx] = self._max_prio

    def sample(self, num_items: int) -> SampleBatch:
        p = self._prio[:self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, num_items, p=p)
        weights = (self._size * p[idx]) ** (-self.beta)
        weights = weights / weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(priorities) + 1e-6
        self._prio[idx] = priorities
        self._max_prio = max(self._max_prio, float(priorities.max()))
