"""Learner: the gradient-update abstraction, compiled onto the device mesh.

Role parity: rllib/core/learner/learner.py:100 (Learner — loss + update)
and learner_group.py:48 (LearnerGroup — 1..N learner actors). TPU-first:
``update`` is ONE jitted function over a Mesh with batch-sharded inputs —
the multi-learner DDP path of the reference collapses into XLA inserting
the gradient psum across the dp axis (SURVEY §3.5 TPU mapping). A
LearnerGroup with a remote learner actor holds the TPU resource; the local
mode runs in-process.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.module import make_module
from ray_tpu.rl.sample_batch import SampleBatch


class PPOLearner:
    """Clipped-surrogate PPO update, jit-compiled once.

    Loss (standard PPO): ratio clip + value clip + entropy bonus; minibatch
    SGD with advantage normalization per minibatch.
    """

    def __init__(self, module_spec: dict, *, lr: float = 3e-4,
                 clip_param: float = 0.2, vf_clip_param: float = 10.0,
                 vf_loss_coeff: float = 0.5, entropy_coeff: float = 0.0,
                 num_sgd_iter: int = 6, sgd_minibatch_size: int = 128,
                 grad_clip: float = 0.5, seed: int = 0,
                 mesh: Optional[Any] = None):
        import jax
        import jax.numpy as jnp
        import optax

        self.module = make_module(module_spec)
        self.num_sgd_iter = num_sgd_iter
        self.minibatch_size = sgd_minibatch_size
        self._rng = np.random.default_rng(seed)
        self.tx = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adam(lr),
        )
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.tx.init(self.params)
        self.mesh = mesh
        module = self.module
        tx = self.tx

        def loss_fn(params, batch):
            logp, entropy, value = module.logp_entropy(
                params, batch[sb.OBS], batch[sb.ACTIONS])
            adv = batch[sb.ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            ratio = jnp.exp(logp - batch[sb.ACTION_LOGP])
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
            pi_loss = -surr.mean()
            vf_err = (value - batch[sb.VALUE_TARGETS]) ** 2
            vf_clipped = batch[sb.VF_PREDS] + jnp.clip(
                value - batch[sb.VF_PREDS], -vf_clip_param, vf_clip_param)
            vf_err2 = (vf_clipped - batch[sb.VALUE_TARGETS]) ** 2
            vf_loss = jnp.maximum(vf_err, vf_err2).mean()
            ent = entropy.mean()
            total = pi_loss + vf_loss_coeff * vf_loss - entropy_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent,
                           "kl": (batch[sb.ACTION_LOGP] - logp).mean()}

        def sgd_step(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            return params, opt_state, stats

        if mesh is not None:
            # Shard the minibatch over the dp axis; params replicated. XLA
            # inserts the gradient all-reduce over ICI.
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
            batch_sh = NamedSharding(mesh, P(dp_axes))
            rep = NamedSharding(mesh, P())
            self._sgd = jax.jit(
                sgd_step,
                in_shardings=(rep, rep, batch_sh),
                out_shardings=(rep, rep, rep))
        else:
            self._sgd = jax.jit(sgd_step)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        """Minibatch-SGD epochs over one train batch."""
        stats = {}
        if batch.count == 0:
            return stats  # faulted rollout round: nothing to learn from
        # 0 => whole batch; larger-than-batch clamps down — minibatches()
        # yields NOTHING when size > count, which would silently skip the
        # update (a real A2C bug class, not a safe no-op).
        size = self.minibatch_size or batch.count
        size = min(size, batch.count)
        for _ in range(self.num_sgd_iter):
            shuffled = batch.shuffle(self._rng)
            for mb in shuffled.minibatches(size):
                self.params, self.opt_state, stats = self._sgd(
                    self.params, self.opt_state, dict(mb))
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.params)

    def set_weights(self, params) -> bool:
        self.params = params
        return True


class LearnerGroup:
    """One learner, local or remote (parity: learner_group.py:48). The
    remote mode puts the learner in its own actor holding the TPU
    resource; weight broadcast to rollout workers goes through the object
    store."""

    def __init__(self, learner_cls, learner_kwargs: dict, *,
                 remote: bool = False, num_tpus: float = 0.0):
        self.remote = remote
        if remote:
            import ray_tpu as rt
            cls = rt.remote(learner_cls)
            self.actor = cls.options(num_cpus=1, num_tpus=num_tpus).remote(
                **learner_kwargs)
        else:
            self.local = learner_cls(**learner_kwargs)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        if self.remote:
            import ray_tpu as rt
            return rt.get(self.actor.update.remote(batch), timeout=600)
        return self.local.update(batch)

    def get_weights(self):
        if self.remote:
            import ray_tpu as rt
            return rt.get(self.actor.get_weights.remote(), timeout=600)
        return self.local.get_weights()

    def shutdown(self):
        if self.remote:
            import ray_tpu as rt
            try:
                rt.kill(self.actor)
            except Exception:
                pass
