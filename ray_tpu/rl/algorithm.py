"""Algorithm base + config: the RL training driver.

Role parity: rllib/algorithms/algorithm.py:149 (Algorithm(Trainable):
setup builds the WorkerSet, train() -> training_step) and
algorithm_config.py:117 (AlgorithmConfig fluent builder). The WorkerSet
(evaluation/worker_set.py:79) is a list of RolloutWorker actors with
fault-tolerant foreach (probe_unhealthy_workers role) and object-store
weight broadcast (sync_weights:384).
"""

from __future__ import annotations

import copy
import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import make_env
from ray_tpu.rl.rollout import RolloutWorker
from ray_tpu.rl.sample_batch import SampleBatch


class AlgorithmConfig:
    """Fluent config (parity: algorithm_config.py:117)."""

    def __init__(self):
        self.env: Any = "CartPole-v1"
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 8
        self.rollout_fragment_length = 64
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.lr = 3e-4
        self.train_batch_size = 1024
        self.model_hiddens = (64, 64)
        # Model catalog knobs (parity: rllib model config / conv_filters).
        self.model_encoder = "mlp"        # "mlp" | "cnn"
        self.model_obs_shape = None       # (H, W, C) when encoder == "cnn"
        self.model_filters = ((16, 3, 2), (32, 3, 2))
        self.seed = 0
        self.learner_remote = False
        self.learner_num_tpus = 0.0
        self.extra: Dict[str, Any] = {}

    def environment(self, env=None, **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        self.extra.update(kwargs)
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def resources(self, *, learner_remote: Optional[bool] = None,
                  learner_num_tpus: Optional[float] = None
                  ) -> "AlgorithmConfig":
        if learner_remote is not None:
            self.learner_remote = learner_remote
        if learner_num_tpus is not None:
            self.learner_num_tpus = learner_num_tpus
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "Algorithm":
        return self.algo_class(self)  # type: ignore[attr-defined]

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)


class WorkerSet:
    """Rollout-worker actors (parity: worker_set.py:79)."""

    def __init__(self, config: AlgorithmConfig, module_spec: dict):
        import ray_tpu as rt
        cls = rt.remote(RolloutWorker)
        self.workers = [
            cls.options(num_cpus=1).remote(
                config.env, module_spec, config.rollout_fragment_length,
                config.num_envs_per_worker, config.gamma, config.lambda_,
                seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_rollout_workers)
        ]

    def sample(self, weights_ref) -> List[SampleBatch]:
        import ray_tpu as rt
        return rt.get([w.sample.remote(weights_ref) for w in self.workers],
                      timeout=600)

    def sync_weights(self, weights) -> Any:
        """Broadcast via one object-store put (parity: sync_weights:384)."""
        import ray_tpu as rt
        return rt.put(weights)

    def episode_stats(self) -> List[dict]:
        import ray_tpu as rt
        return rt.get([w.episode_stats.remote() for w in self.workers],
                      timeout=600)

    def stop(self) -> None:
        import ray_tpu as rt
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass


class Algorithm:
    """Trainable-style driver: .train() one iteration at a time."""

    _default_config: Callable[[], AlgorithmConfig]

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        probe = make_env(config.env, num_envs=1, seed=config.seed)
        self.module_spec = {
            "obs_dim": probe.observation_dim,
            "num_actions": probe.num_actions,
            "hiddens": tuple(config.model_hiddens),
        }
        if probe.num_actions < 0:  # continuous: carry the action dims
            self.module_spec["action_dim"] = getattr(probe, "action_dim", 1)
        if config.model_encoder != "mlp":
            if config.model_encoder != "cnn":
                # "lstm" modules have a sequence-first interface the
                # collector stack doesn't drive; fail at build, not inside
                # a remote worker.
                raise ValueError(
                    f"model_encoder {config.model_encoder!r} is not "
                    "trainable via Algorithm (supported: 'mlp', 'cnn'); "
                    "RecurrentRLModule is a module-level API")
            self.module_spec["encoder"] = "cnn"
            if config.model_obs_shape is None:
                raise ValueError("model_encoder='cnn' requires "
                                 "model_obs_shape=(H, W, C)")
            self.module_spec["obs_shape"] = tuple(config.model_obs_shape)
            self.module_spec["filters"] = tuple(config.model_filters)
        self.setup()

    def setup(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        start = time.time()
        result = self.training_step()
        self.iteration += 1
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": time.time() - start,
        })
        return result

    # -- checkpointing (parity: Trainable.save/restore) ------------------
    def get_state(self) -> dict:
        raise NotImplementedError

    def set_state(self, state: dict) -> None:
        raise NotImplementedError

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(
            prefix="rtpu-algo-")
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump({"iteration": self.iteration,
                         "timesteps_total": self._timesteps_total,
                         "state": self.get_state()}, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            blob = pickle.load(f)
        self.iteration = blob["iteration"]
        self._timesteps_total = blob["timesteps_total"]
        self.set_state(blob["state"])

    def stop(self) -> None:
        pass
