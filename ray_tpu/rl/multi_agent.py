"""Multi-agent sampling: per-agent transitions through shared or mapped
policies.

Role parity: rllib/env/multi_agent_env.py:30 (the dict-keyed protocol in
rl/env.MultiAgentEnv) + the multi-agent half of the sample collector
(rllib/evaluation/env_runner_v2.py): each step, every live agent's
(obs, action, reward, done) lands in the batch of the policy
``policy_mapping_fn`` assigns it to. Parameter sharing (all agents -> one
policy) is the TPU-first default: one jitted forward serves every agent in
a single batched call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.env import MultiAgentEnv
from ray_tpu.rl.sample_batch import SampleBatch

AGENT_ID = "agent_id"


class MultiAgentCollector:
    """Steps one MultiAgentEnv, batching all agents through each policy's
    forward once per step."""

    def __init__(self, env: MultiAgentEnv, modules: Dict[str, Any],
                 params: Dict[str, Any],
                 policy_mapping_fn: Optional[Callable[[str], str]] = None,
                 seed: int = 0):
        import jax
        self.env = env
        self.modules = modules
        self.params = dict(params)
        self.policy_mapping_fn = policy_mapping_fn or (
            lambda agent_id: next(iter(modules)))
        self.key = jax.random.PRNGKey(seed)
        self._sample_fns = {
            pid: jax.jit(m.sample_actions) for pid, m in modules.items()}
        self._obs = env.reset()
        self.episode_returns: List[float] = []
        self._ep_return = 0.0

    def set_params(self, params: Dict[str, Any]) -> None:
        self.params.update(params)

    def collect(self, num_steps: int) -> Dict[str, SampleBatch]:
        """Run ``num_steps`` env steps; returns one SampleBatch per policy
        (rows carry AGENT_ID so callers can regroup)."""
        import jax

        rows: Dict[str, Dict[str, list]] = {
            pid: {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                  sb.NEXT_OBS, sb.DONES, AGENT_ID)}
            for pid in self.modules}
        for _ in range(num_steps):
            # group live agents by policy; ONE batched forward per policy
            by_policy: Dict[str, List[str]] = {}
            for agent in self._obs:
                by_policy.setdefault(
                    self.policy_mapping_fn(agent), []).append(agent)
            actions: Dict[str, Any] = {}
            for pid, agents in by_policy.items():
                obs = np.stack([np.asarray(self._obs[a], np.float32)
                                for a in agents])
                self.key, sub = jax.random.split(self.key)
                a, _logp, _v = self._sample_fns[pid](
                    self.params[pid], obs, sub)
                a = np.asarray(a)
                for i, agent in enumerate(agents):
                    actions[agent] = a[i]
            nxt, rewards, dones, all_done, _infos = self.env.step(actions)
            for pid, agents in by_policy.items():
                r = rows[pid]
                for agent in agents:
                    if agent not in rewards:
                        continue
                    r[sb.OBS].append(np.asarray(self._obs[agent],
                                                np.float32))
                    r[sb.ACTIONS].append(actions[agent])
                    r[sb.REWARDS].append(rewards[agent])
                    r[sb.NEXT_OBS].append(np.asarray(
                        nxt.get(agent, self._obs[agent]), np.float32))
                    r[sb.DONES].append(bool(dones.get(agent, False)))
                    r[AGENT_ID].append(agent)
            self._ep_return += float(sum(rewards.values()))
            if all_done.get("__all__"):
                self.episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs = self.env.reset()
            else:
                self._obs = nxt
        out = {}
        for pid, r in rows.items():
            if not r[sb.OBS]:
                continue
            out[pid] = SampleBatch({
                sb.OBS: np.stack(r[sb.OBS]),
                sb.ACTIONS: np.asarray(r[sb.ACTIONS]),
                sb.REWARDS: np.asarray(r[sb.REWARDS], np.float32),
                sb.NEXT_OBS: np.stack(r[sb.NEXT_OBS]),
                sb.DONES: np.asarray(r[sb.DONES], np.float32),
                AGENT_ID: np.asarray(r[AGENT_ID]),
            })
        return out


class TwoStepCoopEnv(MultiAgentEnv):
    """Tiny cooperative test env (the spirit of rllib's TwoStepGame):
    both agents see the phase; reward 1 each when their actions MATCH,
    0 otherwise; episodes last ``horizon`` steps."""

    def __init__(self, horizon: int = 8, seed: int = 0):
        self.horizon = horizon
        self._t = 0
        self._rng = np.random.default_rng(seed)
        self.observation_dim = 2
        self.num_actions = 2

    def _obs(self):
        phase = self._t / max(self.horizon, 1)
        return {a: np.array([phase, 1.0], np.float32)
                for a in ("agent_0", "agent_1")}

    def reset(self):
        self._t = 0
        return self._obs()

    def step(self, actions):
        self._t += 1
        match = int(actions["agent_0"]) == int(actions["agent_1"])
        rew = {a: 1.0 if match else 0.0 for a in actions}
        done = self._t >= self.horizon
        dones = {a: done for a in actions}
        return self._obs(), rew, dones, {"__all__": done}, {}
