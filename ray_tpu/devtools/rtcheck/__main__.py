from ray_tpu.devtools.rtcheck.core import main

raise SystemExit(main())
