"""rtcheck: distributed-correctness static analysis for the ray_tpu tree.

Role parity: the reference enforces its invariants with a C++ toolchain —
the single ``RAY_CONFIG`` macro registry (src/ray/common/ray_config_def.h),
clang-tidy checks, and ``GUARDED_BY``/TSAN lock-annotation discipline. Our
equivalents (config knobs, ``fault_plane.fire()`` sites, ``rt_*`` metric
names, flight-recorder event kinds, "does no RPC under self._lock"
comments) were convention-only; rtcheck machine-checks them.

Checkers (each one AST-based, cross-file where the invariant is global):

- ``config-drift``    every ``config.get("x")`` / ``set_override("x")``
                      literal must be ``config.define``d; every defined
                      flag must be read somewhere (dead-knob detection);
                      ``define`` with an empty ``doc`` is a finding.
- ``fault-sites``     every ``fire("…")`` literal must be registered in
                      ``fault_plane.SITES``; every registered site must
                      be fired somewhere.
- ``name-drift``      ``rt_*`` metric-name literals outside
                      ``util/metrics.py`` must be minted in
                      ``metrics.METRICS``; ``events.emit`` kind literals
                      must be minted in ``events.EVENT_KINDS``; both
                      registries are checked for dead entries.
- ``lock-blocking``   inside ``with self._lock:`` / ``with self._cv:``
                      bodies (and module-level ``_lock``/``_cv``), calls
                      to known-blocking ops (``time.sleep``, RPC
                      ``call*``, socket send/recv, ``subprocess``,
                      ``.result()``, ``open``) are findings unless the
                      statement carries ``# rtcheck: allow-blocking(why)``.
- ``except-hygiene``  bare ``except:`` / ``except BaseException`` without
                      an annotation, and ``os._exit`` outside the
                      process-termination allowlist.
- ``thread-hygiene``  ``threading.Thread(...)`` must pass ``name=`` and
                      ``daemon=`` explicitly.
- ``doc-drift``       PARITY.md's fault-site table must list every
                      ``SITES`` entry (runs only when PARITY.md exists).

Run: ``python -m ray_tpu.devtools.rtcheck [--json] [paths...]`` — exits
nonzero on findings. A tier-1 test runs the suite over ``ray_tpu/`` and
asserts zero findings, making every checker self-enforcing.

Suppressions are explicit and carry a reason::

    sock.sendall(buf)   # rtcheck: allow-blocking(one serialized socket)

``# noqa: BLE001`` (the pre-existing broad-except convention) is honored
by ``except-hygiene``.
"""

from ray_tpu.devtools.rtcheck.core import Finding, run_tree  # noqa: F401
