"""rtcheck driver: file loading, pragma parsing, registry extraction,
checker orchestration.

Everything is AST-based and import-free: the scanned tree is never
executed, so the checker runs in a bare interpreter in well under the
10s wall-time budget the microbench gates (``rtcheck_full_tree``).

Cross-file invariants (dead knobs, unfired sites, unused metric names)
need the whole package in view, so they only run when the scan covers
the registry sources themselves (``config.py``, ``fault_plane.py``,
``metrics.py``, ``events.py``). A partial scan — one subdirectory —
still runs every local checker plus the "undeclared name" direction of
the registry checkers.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

_PRAGMA_RE = re.compile(r"#\s*rtcheck:\s*allow-([a-z-]+)\(([^)]*)\)")
_NOQA_BROAD_RE = re.compile(r"#\s*noqa:.*\bBLE001\b")


@dataclass(frozen=True)
class Finding:
    checker: str
    path: str          # repo-relative (or as-given) file path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


class SourceFile:
    """One parsed module plus its pragma index."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        # line -> [(rule, reason)]; comment-only pragma lines also cover
        # the statement starting on the next line.
        self._pragmas: Dict[int, List[Tuple[str, str]]] = {}
        self._own_line_pragmas: set = set()
        for i, line in enumerate(self.lines, start=1):
            if "rtcheck:" in line:
                for m in _PRAGMA_RE.finditer(line):
                    self._pragmas.setdefault(i, []).append(
                        (m.group(1), m.group(2).strip()))
                if line.lstrip().startswith("#"):
                    self._own_line_pragmas.add(i)

    def pragma(self, node: ast.AST, rule: str) -> Optional[str]:
        """Reason string if any line of ``node``'s statement span carries
        ``# rtcheck: allow-<rule>(reason)`` (trailing, or on a comment
        line directly above); None otherwise. An empty reason does NOT
        suppress — suppressions must say why."""
        lo = getattr(node, "lineno", 0)
        hi = getattr(node, "end_lineno", lo)
        first = lo - 1 if lo - 1 in self._own_line_pragmas else lo
        for ln in range(first, hi + 1):
            for rule_name, reason in self._pragmas.get(ln, ()):
                if rule_name == rule and reason:
                    return reason
        return None

    def has_broad_except_mark(self, node: ast.AST) -> bool:
        lo = getattr(node, "lineno", 0)
        line = self.lines[lo - 1] if 0 < lo <= len(self.lines) else ""
        return bool(_NOQA_BROAD_RE.search(line)) or bool(
            self.pragma(node, "broad-except"))


@dataclass
class Registries:
    """Canonical-name registries extracted from the scanned tree (or
    injected by tests). ``None`` means the registry source was not in
    the scan, so its dead-entry direction is skipped."""
    config_flags: Optional[Dict[str, Tuple[int, str]]] = None  # name -> (line, doc)
    sites: Optional[Dict[str, int]] = None                     # name -> line
    metrics: Optional[Dict[str, int]] = None
    event_kinds: Optional[Dict[str, int]] = None
    config_path: str = ""
    sites_path: str = ""
    metrics_path: str = ""
    events_path: str = ""
    parity_path: Optional[Path] = None


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _extract_define_calls(sf: SourceFile) -> Dict[str, Tuple[int, str]]:
    """``define("name", type, default, doc)`` calls in a config module."""
    out: Dict[str, Tuple[int, str]] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name != "define" or not node.args:
            continue
        flag = _literal_str(node.args[0])
        if flag is None:
            continue
        doc = ""
        for kw in node.keywords:
            if kw.arg == "doc":
                doc = _literal_str(kw.value) or ""
        if len(node.args) >= 4:
            doc = _literal_str(node.args[3]) or doc
        out[flag] = (node.lineno, doc)
    return out


def _extract_dict_assign(sf: SourceFile, target: str) -> Optional[Dict[str, int]]:
    """Literal string keys of a module-level ``TARGET = {...}`` (or
    ``TARGET: ... = {...}``) assignment."""
    for node in sf.tree.body:
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == target:
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == target:
            value = node.value
        if isinstance(value, ast.Dict):
            out = {}
            for k in value.keys:
                s = _literal_str(k)
                if s is not None:
                    out[s] = k.lineno
            return out
    return None


def load_files(paths: List[Path]) -> List[SourceFile]:
    files: List[SourceFile] = []
    seen = set()
    for p in paths:
        p = Path(p)
        candidates = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in candidates:
            if "__pycache__" in f.parts or f in seen:
                continue
            seen.add(f)
            try:
                rel = str(f.relative_to(Path.cwd()))
            except ValueError:
                rel = str(f)
            files.append(SourceFile(f, rel))
    return files


def extract_registries(files: List[SourceFile]) -> Registries:
    reg = Registries()
    for sf in files:
        name = sf.path.name
        if name == "config.py" and "define(" in sf.text and \
                reg.config_flags is None:
            flags = _extract_define_calls(sf)
            if flags:
                reg.config_flags, reg.config_path = flags, sf.rel
        elif name == "fault_plane.py" and reg.sites is None:
            reg.sites = _extract_dict_assign(sf, "SITES")
            reg.sites_path = sf.rel
        elif name == "metrics.py" and reg.metrics is None:
            reg.metrics = _extract_dict_assign(sf, "METRICS")
            reg.metrics_path = sf.rel
        elif name == "events.py" and reg.event_kinds is None:
            reg.event_kinds = _extract_dict_assign(sf, "EVENT_KINDS")
            reg.events_path = sf.rel
    return reg


def _find_parity(paths: List[Path]) -> Optional[Path]:
    for p in paths:
        cur = Path(p).resolve()
        if cur.is_file():
            cur = cur.parent
        for d in [cur, *cur.parents]:
            cand = d / "PARITY.md"
            if cand.exists():
                return cand
    return None


def run_tree(paths: List, registries: Optional[Registries] = None,
             with_doc_drift: bool = True) -> List[Finding]:
    """Run every checker over ``paths`` (files or directories). Returns
    all findings, sorted by (path, line)."""
    from ray_tpu.devtools.rtcheck import checkers

    paths = [Path(p) for p in paths]
    files = load_files(paths)
    reg = registries if registries is not None else extract_registries(files)
    if with_doc_drift and reg.parity_path is None:
        reg.parity_path = _find_parity(paths)
    findings: List[Finding] = []
    for checker in checkers.build_all(reg):
        for sf in files:
            checker.visit_file(sf)
        findings.extend(checker.finalize())
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


def default_tree_root() -> Path:
    """The installed ray_tpu package root (what ``python -m
    ray_tpu.devtools.rtcheck`` scans when no path is given)."""
    return Path(__file__).resolve().parents[2]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        "rtcheck", description="ray_tpu distributed-correctness checkers")
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the "
                    "installed ray_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)
    paths = args.paths or [default_tree_root()]
    findings = run_tree(paths)
    if args.json:
        print(_json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"rtcheck: {len(findings)} finding(s) over "
              f"{len(paths)} path(s)")
    return 1 if findings else 0
