"""The rtcheck checker implementations (see package docstring for the
rule inventory). Each checker sees every file once, accumulates local
findings immediately, and reports cross-file findings (dead registry
entries) in ``finalize()``."""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.rtcheck.core import (
    Finding, Registries, SourceFile, _literal_str)

_METRIC_RE = re.compile(r"^rt_[a-z0-9_]+$")
_SITE_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_*?]+)+$")


class Checker:
    name = ""

    def __init__(self, reg: Registries):
        self.reg = reg
        self.findings: List[Finding] = []

    def add(self, path: str, line: int, msg: str) -> None:
        self.findings.append(Finding(self.name, path, line, msg))

    def visit_file(self, sf: SourceFile) -> None:  # pragma: no cover
        raise NotImplementedError

    def finalize(self) -> List[Finding]:
        return self.findings


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _config_aliases(sf: SourceFile) -> Set[str]:
    """Names this module binds to the ray_tpu config module."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".")[0] == "ray_tpu":
            for a in node.names:
                if a.name == "config" or a.name.endswith(".config"):
                    out.add(a.asname or "config")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "ray_tpu.config" and a.asname:
                    out.add(a.asname)
    return out


# ----------------------------------------------------------------------
# 1. config-drift
# ----------------------------------------------------------------------
class ConfigDrift(Checker):
    """Literal ``config.get``/``set_override``/``clear_override`` names
    must be defined; defined flags must be read somewhere (dead knob);
    ``define`` must carry a non-empty ``doc``."""

    name = "config-drift"

    def __init__(self, reg: Registries):
        super().__init__(reg)
        self._reads: Set[str] = set()
        self._config_sf: Optional[SourceFile] = None

    def visit_file(self, sf: SourceFile) -> None:
        if self.reg.config_flags is not None and sf.rel == self.reg.config_path:
            self._config_sf = sf
        aliases = _config_aliases(sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _call_name(node)
            flag = _literal_str(node.args[0])
            if flag is None:
                continue
            is_get = (name == "get" and isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id in aliases)
            is_set = name in ("set_override", "clear_override") and (
                isinstance(node.func, ast.Name)
                or (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in aliases))
            if not (is_get or is_set):
                continue
            if is_get:
                self._reads.add(flag)
            if self.reg.config_flags is not None and \
                    flag not in self.reg.config_flags and \
                    not sf.pragma(node, "undeclared-knob"):
                self.add(sf.rel, node.lineno,
                         f"config knob {flag!r} is not config.define()d")

    def finalize(self) -> List[Finding]:
        flags = self.reg.config_flags
        if flags is not None and self._config_sf is not None:
            for flag, (line, doc) in sorted(flags.items()):
                node = _FakeNode(line)
                if flag not in self._reads and \
                        not self._config_sf.pragma(node, "dead-knob"):
                    self.add(self.reg.config_path, line,
                             f"config knob {flag!r} is defined but never "
                             f"read (config.get) anywhere in the tree")
                if not doc.strip() and \
                        not self._config_sf.pragma(node, "undocumented"):
                    self.add(self.reg.config_path, line,
                             f"config knob {flag!r} has an empty doc")
        return self.findings


class _FakeNode:
    def __init__(self, line: int):
        self.lineno = line
        self.end_lineno = line


# ----------------------------------------------------------------------
# 2. fault-sites
# ----------------------------------------------------------------------
class FaultSites(Checker):
    """``fire("a.b.c")`` literals must be registered in
    ``fault_plane.SITES``; registered sites must be fired somewhere."""

    name = "fault-sites"

    def __init__(self, reg: Registries):
        super().__init__(reg)
        self._fired: Set[str] = set()
        self._sites_sf: Optional[SourceFile] = None

    def visit_file(self, sf: SourceFile) -> None:
        if self.reg.sites is not None and sf.rel == self.reg.sites_path:
            self._sites_sf = sf
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node) != "fire":
                continue
            site = _literal_str(node.args[0])
            if site is None or not _SITE_RE.match(site):
                continue
            self._fired.add(site)
            if self.reg.sites is not None and site not in self.reg.sites \
                    and not sf.pragma(node, "unregistered-site"):
                self.add(sf.rel, node.lineno,
                         f"fault site {site!r} is fired but not registered "
                         f"in fault_plane.SITES")

    def finalize(self) -> List[Finding]:
        if self.reg.sites is not None:
            for site, line in sorted(self.reg.sites.items()):
                if site not in self._fired and (
                        self._sites_sf is None or
                        not self._sites_sf.pragma(_FakeNode(line),
                                                  "unfired-site")):
                    self.add(self.reg.sites_path, line,
                             f"fault site {site!r} is registered in SITES "
                             f"but never fired")
        return self.findings


# ----------------------------------------------------------------------
# 3. name-drift (rt_* metrics + flight-recorder event kinds)
# ----------------------------------------------------------------------
class NameDrift(Checker):
    """Every ``rt_*`` metric-name literal outside util/metrics.py must be
    minted in ``metrics.METRICS``; every ``emit("kind")`` literal must be
    minted in ``events.EVENT_KINDS``. Registered names nobody references
    are dead."""

    name = "name-drift"

    def __init__(self, reg: Registries):
        super().__init__(reg)
        self._metric_uses: Set[str] = set()
        self._kind_uses: Set[str] = set()

    def visit_file(self, sf: SourceFile) -> None:
        in_registry = sf.rel == self.reg.metrics_path
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _METRIC_RE.match(node.value) and not in_registry:
                self._metric_uses.add(node.value)
                if self.reg.metrics is not None and \
                        node.value not in self.reg.metrics and \
                        not sf.pragma(node, "unminted-metric"):
                    self.add(sf.rel, node.lineno,
                             f"metric name {node.value!r} is not minted in "
                             f"util/metrics.METRICS")
            if isinstance(node, ast.Call) and node.args and \
                    _call_name(node) in ("emit", "_emit"):
                kind = _literal_str(node.args[0])
                if kind is None:
                    continue
                self._kind_uses.add(kind)
                if self.reg.event_kinds is not None and \
                        kind not in self.reg.event_kinds and \
                        not sf.pragma(node, "unminted-kind"):
                    self.add(sf.rel, node.lineno,
                             f"event kind {kind!r} is not minted in "
                             f"util/events.EVENT_KINDS")

    def finalize(self) -> List[Finding]:
        if self.reg.metrics is not None:
            for name, line in sorted(self.reg.metrics.items()):
                if name not in self._metric_uses:
                    self.add(self.reg.metrics_path, line,
                             f"metric {name!r} is minted in METRICS but "
                             f"never referenced outside the registry")
        if self.reg.event_kinds is not None:
            for kind, line in sorted(self.reg.event_kinds.items()):
                if kind not in self._kind_uses:
                    self.add(self.reg.events_path, line,
                             f"event kind {kind!r} is minted in "
                             f"EVENT_KINDS but never emitted")
        return self.findings


# ----------------------------------------------------------------------
# 4. lock-blocking
# ----------------------------------------------------------------------
_LOCK_ATTRS = {"_lock", "_cv"}
_SOCKET_ATTRS = {"recv", "recv_into", "recvfrom", "send", "sendall",
                 "sendmsg", "accept", "connect", "makefile"}
_SUBPROC_ATTRS = {"Popen", "check_output", "check_call", "communicate"}
_RPC_ATTRS = {"call", "call_async", "call_batch", "call_pipelined"}


def _is_lock_ctx(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Attribute) and expr.attr in _LOCK_ATTRS and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return True
    return isinstance(expr, ast.Name) and expr.id in _LOCK_ATTRS


def _classify_blocking(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name):
        return "open() file I/O" if fn.id == "open" else None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    base = fn.value.id if isinstance(fn.value, ast.Name) else ""
    if attr == "sleep":
        return "time.sleep"
    if attr in _RPC_ATTRS:
        return f"RPC .{attr}()"
    if attr == "result":
        return "future .result() wait"
    if attr in _SOCKET_ATTRS:
        return f"socket .{attr}()"
    if attr in _SUBPROC_ATTRS or (attr in ("run",) and base == "subprocess"):
        return f"subprocess .{attr}()"
    if attr == "get" and base in ("rt", "ray_tpu"):
        return f"{base}.get() object wait"
    return None


_SKIP_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)


def _iter_stmts(body: Iterable[ast.stmt]) -> Iterable[ast.stmt]:
    """Statements executed while the lock is held: recurse into compound
    statements but NOT into nested def/class bodies (those run later,
    without the lock)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, _SKIP_SCOPES):
            continue
        for field_name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field_name, None)
            if sub:
                yield from _iter_stmts(sub)
        for handler in getattr(stmt, "handlers", ()):
            yield from _iter_stmts(handler.body)


def _stmt_calls(stmt: ast.stmt) -> Iterable[ast.Call]:
    """Calls evaluated by this statement itself (its header expressions),
    excluding nested statements and deferred scopes (lambda bodies)."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt) or \
                    isinstance(child, _SKIP_SCOPES):
                continue
            stack.append(child)


class LockBlocking(Checker):
    """No known-blocking call inside a ``with self._lock:`` /
    ``with self._cv:`` body. The conductor/daemon contracts ("does no
    RPC under self._lock") live here now, not in comments. Suppress a
    deliberate hold with ``# rtcheck: allow-blocking(reason)`` on the
    statement."""

    name = "lock-blocking"

    def __init__(self, reg: Registries):
        super().__init__(reg)
        self._seen: Set[Tuple[str, int, str]] = set()

    def visit_file(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_names = [ast.unparse(i.context_expr) for i in node.items
                          if _is_lock_ctx(i.context_expr)]
            if not lock_names:
                continue
            for stmt in _iter_stmts(node.body):
                for call in _stmt_calls(stmt):
                    why = _classify_blocking(call)
                    if why is None:
                        continue
                    key = (sf.rel, call.lineno, why)
                    if key in self._seen:
                        continue  # nested with-blocks: report once
                    self._seen.add(key)
                    if sf.pragma(stmt, "blocking") or \
                            sf.pragma(call, "blocking"):
                        continue
                    self.add(sf.rel, call.lineno,
                             f"{why} while holding {lock_names[0]} "
                             f"(annotate # rtcheck: allow-blocking(why) "
                             f"if deliberate)")


# ----------------------------------------------------------------------
# 5. except-hygiene
# ----------------------------------------------------------------------
_EXIT_ALLOWED_FILES = {"fault_plane.py", "worker_main.py"}


def _mentions_base_exception(expr: Optional[ast.AST]) -> bool:
    if expr is None:
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id == "BaseException":
            return True
    return False


class ExceptHygiene(Checker):
    """Bare ``except:`` / ``except BaseException`` can swallow
    KeyboardInterrupt and worker-kill signals; each one must be annotated
    (``# noqa: BLE001`` or an rtcheck pragma) or narrowed. ``os._exit``
    bypasses finally/atexit and is reserved for the process-termination
    planes (fault_plane, worker_main)."""

    name = "except-hygiene"

    def visit_file(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    if not sf.pragma(node, "bare-except") and \
                            not sf.has_broad_except_mark(node):
                        self.add(sf.rel, node.lineno,
                                 "bare 'except:' (swallows "
                                 "KeyboardInterrupt/SystemExit) — narrow "
                                 "it or annotate why")
                elif _mentions_base_exception(node.type) and \
                        not sf.has_broad_except_mark(node):
                    self.add(sf.rel, node.lineno,
                             "'except BaseException' without an "
                             "annotation — narrow it or mark "
                             "# noqa: BLE001 with a reason")
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "_exit" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "os":
                if sf.path.name not in _EXIT_ALLOWED_FILES and \
                        not sf.pragma(node, "exit"):
                    self.add(sf.rel, node.lineno,
                             "os._exit outside fault_plane/worker_main "
                             "(skips finally/atexit cleanup)")


# ----------------------------------------------------------------------
# 6. thread-hygiene
# ----------------------------------------------------------------------
class ThreadHygiene(Checker):
    """Every ``threading.Thread(...)`` must pass ``name=`` (debug_state /
    py-spy profiles become unreadable with Thread-12 soup) and an explicit
    ``daemon=`` (implicit non-daemon threads hang interpreter exit)."""

    name = "thread-hygiene"

    def visit_file(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_thread = (isinstance(fn, ast.Name) and fn.id == "Thread") or \
                (isinstance(fn, ast.Attribute) and fn.attr == "Thread")
            if not is_thread:
                continue
            kwargs = {kw.arg for kw in node.keywords}
            missing = [k for k in ("name", "daemon") if k not in kwargs]
            if missing and not sf.pragma(node, "thread"):
                self.add(sf.rel, node.lineno,
                         f"threading.Thread without {'/'.join(missing)}=")


# ----------------------------------------------------------------------
# 7. doc-drift (PARITY.md fault-site table vs SITES)
# ----------------------------------------------------------------------
class DocDrift(Checker):
    """PARITY.md's fault-site table and ``fault_plane.SITES`` must not
    drift: every registered site appears in PARITY.md, and every site the
    r15 table lists is registered."""

    name = "doc-drift"

    def visit_file(self, sf: SourceFile) -> None:
        pass

    def finalize(self) -> List[Finding]:
        reg = self.reg
        if reg.sites is None or reg.parity_path is None or \
                not reg.parity_path.exists():
            return self.findings
        text = reg.parity_path.read_text()
        rel = str(reg.parity_path)
        for site in sorted(reg.sites):
            if site not in text:
                self.add(rel, 1, f"fault site {site!r} is registered in "
                         f"SITES but missing from PARITY.md")
        # Reverse direction: sites the dedicated table claims.
        in_table = False
        for i, line in enumerate(text.splitlines(), start=1):
            if "Fault-site registry" in line:
                in_table = True
                continue
            if in_table and line.startswith("#"):
                break
            if in_table and line.startswith("|"):
                for m in re.finditer(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`",
                                     line):
                    if m.group(1) not in reg.sites:
                        self.add(rel, i,
                                 f"PARITY.md fault-site table lists "
                                 f"{m.group(1)!r} which is not in SITES")
        return self.findings


def build_all(reg: Registries) -> List[Checker]:
    return [ConfigDrift(reg), FaultSites(reg), NameDrift(reg),
            LockBlocking(reg), ExceptHygiene(reg), ThreadHygiene(reg),
            DocDrift(reg)]
