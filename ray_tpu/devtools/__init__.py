"""Developer tooling that ships with the runtime (static analysis,
registries introspection). Nothing here is imported by production code
paths; tier-1 tests run the checkers over the tree so every PR is gated
without external CI."""
